"""Binary batched wire protocol v2 ("B2") shared by clients and servers.

The serving plane speaks two framings over the same TCP port:

* **tab** (v1) — one ``\\t``-separated request line per query, one reply line
  per request.  This is the only framing an un-negotiated connection may use,
  and it is frozen: old clients stay byte-identical on the wire (pinned by
  ``tests/test_native_protocol.py``).
* **B2** (v2) — a length-prefixed batch frame negotiated by sending the text
  line ``HELLO\\tB2`` as the first request.  A server that understands v2
  answers ``HELLO\\tB2`` and both directions switch to binary frames; an old
  server answers ``E\\tbad request`` and the client falls back to tab.

Frame layout (both directions)::

    b"B2"  varint(body_len)  body
    body = varint(record_count)  record*

A *request* record is one opcode byte followed by the tab-protocol fields for
that verb (everything after the verb token), each encoded as
``varint(len) + utf8 bytes``.  A *reply* record is ``varint(len) + bytes`` of
exactly the tab-protocol reply line without its trailing newline — so binary
and tab replies are equal by construction, per verb.

varints are unsigned LEB128 (7 bits per byte, little-endian), capped at 10
bytes.  Structural corruption (bad magic, oversized frame, truncated body,
unknown opcode, trailing bytes) raises :class:`ProtoError`; servers answer a
single-record error frame ``E\\tbad frame: <reason>`` and close.  Field
*content* is unconstrained bytes-of-UTF-8 — keys containing ``\\x85`` or
``\\u2028`` style separators round-trip unharmed (see ``scripts/proto_fuzz.py``).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

MAGIC = b"B2"

# The negotiation handshake, sent as a plain tab-protocol line.  Optional
# extensions ride as extra tab fields, each self-describing: ``tn=<tenant>``
# (admission identity, serve/admission.py), ``tr=1`` (per-record trace
# field, obs/tracing.py), ``st=1`` (per-read staleness reporting,
# serve/georepl.py — every reply record gains a trailing ``st=<seconds>``
# field) and ``su=1`` (push plane, serve/push.py — the client accepts
# UNSOLICITED ``PUSH\t`` frames between replies; SUBSCRIBE on a B2
# connection requires it).  A HELLO with any OTHER extra field is malformed
# and answers ``E\tbad request`` — pinned, so old and new servers refuse
# unknown extensions identically (the native C++ plane refuses ``su=1``
# this way: push serving is Python-plane only).  The accept reply stays
# the frozen two-field line either way.
HELLO_VERB = "HELLO"
HELLO_LINE = "HELLO\tB2"
HELLO_REPLY = "HELLO\tB2"
TRACE_EXT = "tr=1"
STALE_EXT = "st=1"
PUSH_EXT = "su=1"
STALE_FIELD = "st="  # request: trailing tab field opting one read into
                     # staleness reporting; reply: trailing ``st=<seconds>``
_TENANT_FIELD = "tn="  # mirrors serve/admission.py TENANT_FIELD (no import:
                       # proto stays dependency-free)


def parse_hello(parts: Sequence[str]) -> Optional[dict]:
    """Validate a split HELLO line -> ``{"proto", "tenant", "trace",
    "stale", "push"}`` or None when structurally malformed (unknown
    extension, duplicate tenant).  The caller still refuses protos other
    than ``B2``."""
    if len(parts) < 2 or parts[0] != HELLO_VERB:
        return None
    tenant: Optional[str] = None
    trace = False
    stale = False
    push = False
    for ext in parts[2:]:
        if ext.startswith(_TENANT_FIELD) and tenant is None:
            tenant = ext[len(_TENANT_FIELD):]
        elif ext == TRACE_EXT and not trace:
            trace = True
        elif ext == STALE_EXT and not stale:
            stale = True
        elif ext == PUSH_EXT and not push:
            push = True
        else:
            return None
    return {"proto": parts[1], "tenant": tenant, "trace": trace,
            "stale": stale, "push": push}


# Push frames (serve/push.py).  A ``su=1`` connection may receive
# unsolicited single-text reply records ``PUSH\t<sub_id>\t<seq>\t<payload>``
# interleaved between (never inside) ordinary replies.  The token is
# deliberately NOT a single letter: ``P\t`` already belongs to the
# PROFILE reply and ``PONG`` to PING, and a client must be able to route
# a decoded text by prefix alone without consulting its in-flight window.
PUSH_PREFIX = "PUSH\t"


def is_push_text(text: str) -> bool:
    """True when a decoded reply text is an unsolicited push frame."""
    return text.startswith(PUSH_PREFIX)


def pop_stale(parts: List[str]) -> bool:
    """Pop a strictly-trailing ``st=1`` staleness opt-in field off a split
    tab request -> True when present.  Mirrors ``admission.pop_tenant`` /
    ``tracing.pop_tid``: append order on the wire is ``st=`` then ``tn=``
    then ``tid=``, so the server pops tid, tenant, stale."""
    if len(parts) > 1 and parts[-1] == STALE_EXT:
        parts.pop()
        return True
    return False

# Opcode byte per verb.  Order is frozen; new verbs append.
OPCODES = {
    "GET": 1,
    "MGET": 2,
    "TOPK": 3,
    "TOPKV": 4,
    "DOT": 5,
    "COUNT": 6,
    "HEALTH": 7,
    "METRICS": 8,
    "PING": 9,
    "SUBSCRIBE": 10,
    "RESUME": 11,
    "UNSUB": 12,
}
VERB_BY_OP = {op: verb for verb, op in OPCODES.items()}

# Number of length-prefixed fields following the opcode byte.  Fields are the
# tab-protocol parts after the verb, in order (MGET keeps its comma-joined key
# list as one field — key charset rules are identical to the tab protocol).
FIELD_COUNTS = {
    "GET": 2,      # state, key
    "MGET": 2,     # state, keys_csv
    "TOPK": 3,     # state, id, k
    "TOPKV": 3,    # state, k, payload
    "DOT": 3,      # state, range, payload
    "COUNT": 1,    # state
    "HEALTH": 1,   # state
    "METRICS": 0,
    "PING": 0,
    "SUBSCRIBE": 4,  # state, kind (KEY|TOPK), arg, k
    "RESUME": 5,     # state, kind, arg, k, cursor ("<sub_id>:<seq>")
    "UNSUB": 1,      # sub_id
}

# Caps.  Requests are client-authored and small; replies can carry wide MGET /
# TOPK payloads so get more headroom.  Both ends enforce their receive-side cap.
MAX_REQUEST_BODY = 8 << 20
MAX_REPLY_BODY = 64 << 20
_MAX_VARINT_BYTES = 10


class ProtoError(ValueError):
    """Structurally malformed B2 frame (not a per-verb semantic error)."""


def encode_varint(n: int) -> bytes:
    if n < 0:
        raise ProtoError("bad varint")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf, pos: int) -> Optional[Tuple[int, int]]:
    """Decode an unsigned LEB128 at ``buf[pos:]``.

    Returns ``(value, next_pos)``, or ``None`` if the buffer ends before the
    varint terminates.  Raises :class:`ProtoError` once the encoding provably
    exceeds the 10-byte cap.
    """
    shift = 0
    value = 0
    end = len(buf)
    for i in range(_MAX_VARINT_BYTES):
        if pos + i >= end:
            return None
        b = buf[pos + i]
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos + i + 1
        shift += 7
    raise ProtoError("bad varint")


# hot-path tables: one-byte varints cover every realistic field length and
# record count, so the encoders below index these instead of calling
# encode_varint per field (the codec runs once per request on the client
# AND once per request on the Python server — it must stay in the noise
# next to a ~1.5 us/req pipelined tab round trip)
_B1 = [bytes([i]) for i in range(0x80)]
_OPCODE_BYTES = {verb: bytes([op]) for verb, op in OPCODES.items()}


def record_from_line(line: str, tid: Optional[str] = None) -> bytes:
    """Encode one tab-protocol request line as a B2 request record.

    ``tid`` is only legal on a ``tr=1``-negotiated connection: the record
    grows exactly one trailing length-prefixed field carrying the raw wire
    tid (empty = this record untraced).  Without negotiation the layout is
    the frozen v2 record, byte-identical to the seed encoder.
    """
    parts = line.split("\t")
    verb = parts[0]
    opb = _OPCODE_BYTES.get(verb)
    if opb is None:
        raise ProtoError("unknown verb: %s" % verb)
    nfields = FIELD_COUNTS[verb]
    if len(parts) - 1 != nfields:
        raise ProtoError("verb %s takes %d fields, got %d" % (verb, nfields, len(parts) - 1))
    if tid is not None:
        parts = parts + [tid]
    pieces = [opb]
    for f in parts[1:]:
        raw = f.encode("utf-8")
        n = len(raw)
        pieces.append(_B1[n] if n < 0x80 else encode_varint(n))
        pieces.append(raw)
    return b"".join(pieces)


def record_to_parts(body, pos: int, end: int,
                    trace: bool = False) -> Tuple[List[str], int]:
    """Decode one request record from ``body[pos:end]``.

    Returns ``(parts, next_pos)`` where ``parts`` is the tab-protocol parts
    list (verb first).  On a ``trace`` (``tr=1``) connection every record
    carries one extra trailing field; when non-empty it is surfaced as a
    trailing ``tid=<raw>`` part, exactly where the tab plane's
    ``pop_tid`` expects it.  Raises :class:`ProtoError` on structural
    corruption.
    """
    if pos >= end:
        raise ProtoError("bad body")
    op = body[pos]
    pos += 1
    verb = VERB_BY_OP.get(op)
    if verb is None:
        raise ProtoError("bad body")
    parts = [verb]
    for _ in range(FIELD_COUNTS[verb] + (1 if trace else 0)):
        if pos >= end:
            raise ProtoError("bad body")
        flen = body[pos]
        if flen < 0x80:  # one-byte varint fast path
            pos += 1
        else:
            dv = decode_varint(body, pos)
            if dv is None:
                raise ProtoError("bad body")
            flen, pos = dv
        if pos + flen > end:
            raise ProtoError("bad body")
        try:
            parts.append(bytes(body[pos:pos + flen]).decode("utf-8"))
        except UnicodeDecodeError:
            raise ProtoError("bad body")
        pos += flen
    if trace:
        raw_tid = parts.pop()
        if raw_tid:
            parts.append("tid=" + raw_tid)
    return parts, pos


def encode_request_frame(lines: Sequence[str],
                         tids: Optional[Sequence[Optional[str]]] = None
                         ) -> bytes:
    """Encode a batch of tab-protocol request lines as one B2 frame.
    ``tids`` (tr=1 connections only) aligns with ``lines``; None entries
    encode as the empty trace field."""
    n = len(lines)
    pieces = [_B1[n] if n < 0x80 else encode_varint(n)]
    body_len = len(pieces[0])
    for i, line in enumerate(lines):
        rec = record_from_line(
            line, None if tids is None else (tids[i] or ""))
        body_len += len(rec)
        pieces.append(rec)
    if body_len > MAX_REQUEST_BODY:
        raise ProtoError("frame too large")
    return MAGIC + encode_varint(body_len) + b"".join(pieces)


def _decode_frame(buf, pos: int, max_body: int) -> Optional[Tuple[int, int]]:
    """Common header parse: returns ``(body_start, body_end)`` offsets into
    ``buf`` or None if incomplete."""
    avail = len(buf) - pos
    if avail < 1:
        return None
    if buf[pos] != 0x42 or (avail >= 2 and buf[pos + 1] != 0x32):  # b"B2"
        raise ProtoError("bad magic")
    if avail < 2:
        return None
    dv = decode_varint(buf, pos + 2)
    if dv is None:
        return None
    body_len, body_start = dv
    if body_len > max_body:
        raise ProtoError("frame too large")
    if len(buf) - body_start < body_len:
        return None
    return body_start, body_start + body_len


def decode_request_frame(buf, pos: int = 0, trace: bool = False
                         ) -> Optional[Tuple[List[List[str]], int]]:
    """Decode one request frame from ``buf[pos:]``.

    Returns ``(records, next_pos)`` where each record is a parts list, or
    ``None`` when the buffer does not yet hold a complete frame.  Raises
    :class:`ProtoError` on structural corruption.  ``trace`` reflects the
    connection's ``tr=1`` negotiation (see :func:`record_to_parts`).
    """
    if isinstance(buf, memoryview):
        buf = buf.tobytes()
    hdr = _decode_frame(buf, pos, MAX_REQUEST_BODY)
    if hdr is None:
        return None
    rpos, end = hdr
    dv = decode_varint(buf, rpos)
    if dv is None or dv[1] > end:
        raise ProtoError("bad body")
    count, rpos = dv
    records: List[List[str]] = []
    for _ in range(count):
        parts, rpos = record_to_parts(buf, rpos, end, trace)
        records.append(parts)
    if rpos != end:
        raise ProtoError("bad body")
    return records, end


def encode_reply_frame(texts: Sequence[str]) -> bytes:
    """Encode reply lines (without trailing newlines) as one B2 frame."""
    n = len(texts)
    pieces = [_B1[n] if n < 0x80 else encode_varint(n)]
    body_len = len(pieces[0])
    for t in texts:
        raw = t.encode("utf-8")
        tlen = len(raw)
        pre = _B1[tlen] if tlen < 0x80 else encode_varint(tlen)
        body_len += len(pre) + tlen
        pieces.append(pre)
        pieces.append(raw)
    return MAGIC + encode_varint(body_len) + b"".join(pieces)


def decode_reply_frame(buf, pos: int = 0) -> Optional[Tuple[List[str], int]]:
    """Decode one reply frame from ``buf[pos:]`` (None when incomplete)."""
    if isinstance(buf, memoryview):
        buf = buf.tobytes()
    hdr = _decode_frame(buf, pos, MAX_REPLY_BODY)
    if hdr is None:
        return None
    rpos, end = hdr
    dv = decode_varint(buf, rpos)
    if dv is None or dv[1] > end:
        raise ProtoError("bad body")
    count, rpos = dv
    texts: List[str] = []
    for _ in range(count):
        if rpos >= end:
            raise ProtoError("bad body")
        tlen = buf[rpos]
        if tlen < 0x80:  # one-byte varint fast path
            rpos += 1
        else:
            dv = decode_varint(buf, rpos)
            if dv is None:
                raise ProtoError("bad body")
            tlen, rpos = dv
        if rpos + tlen > end:
            raise ProtoError("bad body")
        try:
            texts.append(buf[rpos:rpos + tlen].decode("utf-8"))
        except UnicodeDecodeError:
            raise ProtoError("bad body")
        rpos += tlen
    if rpos != end:
        raise ProtoError("bad body")
    return texts, end


def error_frame(reason: str) -> bytes:
    """The single-record frame servers send before closing a corrupt stream."""
    return encode_reply_frame(["E\tbad frame: " + reason])


class FrameReader:
    """Blocking reply-frame reader over a file-like socket reader.

    Keeps leftover bytes between calls so back-to-back frames that arrive in
    one TCP segment are not lost — required by the pipelined client, which can
    have several reply frames in flight.
    """

    def __init__(self, rfile):
        self._rfile = rfile
        self._buf = bytearray()

    def poll_frame(self) -> Optional[List[str]]:
        """Decode one already-buffered frame without touching the socket
        (None when the buffer holds no complete frame).  Push-capable
        clients poll this before selecting on the socket: a PUSH frame
        that arrived in the same TCP segment as a reply sits in this
        buffer, invisible to select."""
        res = decode_reply_frame(self._buf)
        if res is None:
            return None
        texts, consumed = res
        del self._buf[:consumed]
        return texts

    def read_frame(self) -> List[str]:
        """Read one reply frame.

        Raises :class:`ProtoError` on corruption and :class:`ConnectionError`
        on EOF mid-frame (including EOF before any bytes, so callers can
        treat it like a dropped connection and retry).
        """
        rfile = self._rfile
        while True:
            res = decode_reply_frame(self._buf)
            if res is not None:
                texts, consumed = res
                del self._buf[:consumed]
                return texts
            chunk = rfile.read1(65536) if hasattr(rfile, "read1") else rfile.read(65536)
            if not chunk:
                raise ConnectionError("EOF mid-frame (%d bytes buffered)" % len(self._buf))
            self._buf += chunk
