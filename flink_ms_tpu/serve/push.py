"""Push plane: subscribed queries and materialized top-k deltas.

The reference's loop ends at queryable state — online SGD updates land in
the serving tables and every client POLLS them.  This module inverts the
last hop: a client SUBSCRIBEs to a key or a top-k query once, the engine
materializes the answer, and each update batch pushes score/membership
DELTAS over the already-open connection instead of being re-asked.

Wire surface (serve/proto.py; servers answer, engines never read)::

    SUBSCRIBE\t<state>\t<kind>\t<arg>\t<k>
        kind KEY : arg is the key, k is ignored ("0" by convention)
        kind TOPK: arg is the query-factor payload ``f1;f2;...``
        -> S\t<sub_id>\t<seq>\t<snapshot>      (seq 0 baseline)
    RESUME\t<state>\t<kind>\t<arg>\t<k>\t<sub_id>:<seq>
        -> R\t<sub_id>\t<from_seq>             then the missed deltas
           replayed as ordinary PUSH frames (ring hit), or
        -> S\t<new_sub_id>\t0\t<snapshot>      (ring miss / unknown sub /
           different replica: a FRESH subscription whose snapshot IS the
           catch-up — new id, new sequence space)
    UNSUB\t<sub_id>
        -> U\t<sub_id>
    pushes: PUSH\t<sub_id>\t<seq>\t<payload>   (unsolicited, between —
           never inside — ordinary replies)

Delta payloads are ``;``-joined entries: ``+item:score`` (entered the
shortlist, or its score changed) and ``-item`` (evicted).  KEY deltas
carry the new value verbatim.  Snapshots carry the full materialized
answer (``item:score;...`` / the value).

Delivery contract — the invariant the chaos arm audits: per subscription
id, sequence numbers are strictly contiguous from the S baseline.  A gap
is a missed notification, a repeat is a duplicate; ``audit_push_sequences``
(the PR-9 ``audit_partitions`` idea applied to subscription streams)
counts both, tiled by subscription.  Reshards, replica kills and region
failovers stay inside the contract because a RESUME that cannot replay
NEVER reuses the old id: subscription ids are ``<epoch>-<n>`` with the
epoch CAS-claimed from the registry (``registry.next_push_epoch``), so a
replacement replica mints ids in a fresh sequence space and bridges the
client with a snapshot instead of guessing at the old stream.

Re-score work scales with the subscriptions an update batch can actually
affect, not with the subscription population:

* KEY subs are a direct hash — ``(state, key) -> sub ids``.
* TOPK subs intersect a dirty batch two ways, both cheap: a MEMBER index
  from shortlist items to sub ids (an update to a current member may
  re-rank or evict it), and an ENTRANT filter — one ``Q @ V.T`` matmul of
  every stacked query vector against the batch's changed rows, compared
  row-wise against each sub's materialized admission threshold (its
  current k-th score; a short shortlist admits anything).  When the index
  runs the IVF tier (serve/ann.py), a sub only probes ``nprobe`` centroid
  lists, so entrant candidates are additionally narrowed to dirty rows
  whose centroid falls in the sub's probed set — the posting lists give
  the candidate index nearly for free.
* Candidates re-score through ``DeviceFactorIndex.topk_many`` — ONE
  batched device dispatch per (state, k) group, not one per subscription.

Knobs: ``TPUMS_PUSH_RING`` (per-sub replay ring length, default 256),
``TPUMS_PUSH_MAX_SUBS`` (engine-wide cap, default 65536),
``TPUMS_PUSH_SCORE_EPS`` (min score change worth a delta, default 0 =
any change).

Freshness caveat, stated honestly: the engine re-scores through the same
serve-stale top-k index queries use, so a structural change that kicks a
BACKGROUND index rebuild is reflected at the next dirty batch after the
rebuild lands, exactly like polled queries observe it.  Sequence
contiguity (the audited invariant) is unaffected.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..obs import metrics as obs_metrics
from . import proto

KIND_KEY = "KEY"
KIND_TOPK = "TOPK"

_NEG_INF = float("-inf")


def ring_capacity() -> int:
    try:
        return max(int(os.environ.get("TPUMS_PUSH_RING", 256)), 1)
    except ValueError:
        return 256


def max_subscriptions() -> int:
    try:
        return max(int(os.environ.get("TPUMS_PUSH_MAX_SUBS", 65536)), 1)
    except ValueError:
        return 65536


def score_eps() -> float:
    try:
        return max(float(os.environ.get("TPUMS_PUSH_SCORE_EPS", 0.0)), 0.0)
    except ValueError:
        return 0.0


class PushError(ValueError):
    """Semantically invalid subscribe/resume/unsub (server answers E)."""


def format_push(sub_id: str, seq: int, payload: str) -> str:
    return f"{proto.PUSH_PREFIX}{sub_id}\t{seq}\t{payload}"


def parse_push(text: str) -> Tuple[str, int, str]:
    """``PUSH\\t<sub_id>\\t<seq>\\t<payload>`` -> (sub_id, seq, payload).
    Raises ValueError on anything else — push routing is prefix-based, so
    a frame that matched the prefix but not the shape is corruption."""
    parts = text.split("\t", 3)
    if len(parts) != 4 or parts[0] != "PUSH":
        raise ValueError(f"not a push frame: {text[:40]!r}")
    return parts[1], int(parts[2]), parts[3]


def apply_delta(shortlist: Dict[str, float], payload: str) -> None:
    """Fold one TOPK delta payload into a client-side shortlist dict —
    the client half of the materialization contract (tests and the
    rehearsal subscribers use it; a real device client would too)."""
    for entry in payload.split(";"):
        if not entry:
            continue
        if entry.startswith("-"):
            shortlist.pop(entry[1:], None)
        elif entry.startswith("+"):
            item, _, score = entry[1:].rpartition(":")
            shortlist[item] = float(score)
        else:
            raise ValueError(f"bad delta entry: {entry[:40]!r}")


class _Subscription:
    __slots__ = ("sub_id", "state", "kind", "arg", "k", "vec", "seq",
                 "ring", "sink", "scores", "last_value", "threshold",
                 "probe_cache")

    def __init__(self, sub_id: str, state: str, kind: str, arg: str,
                 k: int, sink):
        self.sub_id = sub_id
        self.state = state
        self.kind = kind
        self.arg = arg
        self.k = k
        self.vec: Optional[np.ndarray] = None  # TOPK query factors
        self.seq = 0  # the S baseline; first delta is 1
        self.ring: deque = deque()  # (seq, payload), contiguous
        self.sink = sink
        self.scores: Dict[str, float] = {}  # TOPK materialized shortlist
        self.last_value: Optional[str] = None  # KEY last pushed value
        # admission threshold: current k-th score; -inf while the
        # shortlist is short of k (anything can enter)
        self.threshold = _NEG_INF
        # (ann identity token, probed-centroid id set) — recomputed when
        # the index swaps in a different ANN build
        self.probe_cache: Optional[Tuple[int, Set[int]]] = None


class PushEngine:
    """Materialized-subscription engine for one serving process.

    Change feed: a batched table listener per state (the same hook the
    top-k index's dirty set rides) that only ENQUEUES — it runs on the
    writer thread under the table lock, so the O(candidates) work happens
    on the engine's own thread.  Sinks (one per connection, owned by the
    server) expose ``send_push(text)``, ``defer(texts)`` and ``arm()``;
    ``arm`` is called while the subscribe/resume reply is still pending
    so deltas can never overtake their own baseline on the wire."""

    def __init__(self, tables: Dict[str, object],
                 topk_handlers: Optional[Dict[str, object]] = None,
                 scope: str = "local"):
        self.tables = tables
        self.topk_handlers = topk_handlers or {}
        self.scope = scope
        self.epoch = self._claim_epoch(scope)
        self.ring_cap = ring_capacity()
        self.max_subs = max_subscriptions()
        self.score_eps = score_eps()
        self._lock = threading.RLock()  # subs + indexes + processing
        self._subs: Dict[str, _Subscription] = {}
        self._next_n = 0
        self._key_index: Dict[Tuple[str, str], Set[str]] = {}
        self._member_index: Dict[Tuple[str, str], Set[str]] = {}
        self._topk_subs: Dict[str, Set[str]] = {}  # state -> sub ids
        # dirty feed: tiny dedicated lock — the listener runs under the
        # TABLE lock, and the worker holds self._lock while reading
        # tables, so routing the feed through self._lock would deadlock
        self._dirty_lock = threading.Lock()
        self._dirty_cond = threading.Condition(self._dirty_lock)
        self._pending: List[Tuple[str, tuple, float]] = []
        self._has_subs = False  # lock-free fast path for the listener
        self._listened: Set[str] = set()
        self._closed = False
        # plain counters tests/bench read directly (the metric series
        # below are the fleet-facing copies)
        self.deltas = 0
        self.rescored = 0
        self.batches = 0
        self.candidates = 0
        self.candidate_total = 0  # sum of per-batch TOPK populations
        reg = obs_metrics.get_registry()
        self._obs_ring_evictions = reg.counter(
            "tpums_push_ring_evictions_total")
        self._obs_resume = {
            "replay": reg.counter("tpums_push_resume_total",
                                  result="replay"),
            "snapshot": reg.counter("tpums_push_resume_total",
                                    result="snapshot"),
        }
        self._obs_deltas: Dict[Tuple[str, str], object] = {}
        self._obs_latency: Dict[str, object] = {}
        self._obs_subs: Dict[Tuple[str, str], object] = {}
        self._obs_rescored: Dict[str, object] = {}
        self._obs_selectivity: Dict[str, object] = {}
        self._thread = threading.Thread(
            target=self._run, name="push-engine", daemon=True)
        self._thread.start()

    @staticmethod
    def _claim_epoch(scope: str) -> int:
        try:
            from . import registry as _registry

            return _registry.next_push_epoch(scope)
        except Exception:
            # registry unreachable (read-only disk, lock timeout): fall
            # back to a time-derived epoch — still fresh across restarts
            # with overwhelming probability, and the audit treats an id
            # collision as duplicates, i.e. LOUD, not silent
            return int(time.time() * 1000) % (1 << 31) + os.getpid()

    # ------------------------------------------------------------------
    # change feed
    # ------------------------------------------------------------------

    def watch_table(self, state: str) -> None:
        """Attach the dirty listener to a state's table (idempotent).
        Registering a listener forces the consumer's Python ingest path,
        exactly like the top-k index's dirty set does — which is why the
        server only builds an engine on the FIRST subscribe."""
        with self._lock:
            if state in self._listened:
                return
            table = self.tables.get(state)
            if table is None or not hasattr(table, "add_change_listener"):
                raise PushError(f"unknown state: {state}")
            self._listened.add(state)
        table.add_change_listener(
            lambda key, _s=state: self._notify(_s, (key,)),
            batch_fn=lambda keys, _s=state: self._notify(_s, tuple(keys)))

    def _notify(self, state: str, keys: tuple) -> None:
        """Writer-thread hook: enqueue only (the table lock is held)."""
        if not self._has_subs or self._closed or not keys:
            return
        now = time.perf_counter()
        with self._dirty_cond:
            self._pending.append((state, keys, now))
            self._dirty_cond.notify()

    def _run(self) -> None:
        while True:
            with self._dirty_cond:
                while not self._pending and not self._closed:
                    self._dirty_cond.wait(timeout=1.0)
                if self._closed:
                    return
                batch, self._pending = self._pending, []
            # merge per state; the earliest enqueue stamps the batch (the
            # push-latency histogram measures worst-case update->push)
            merged: Dict[str, Tuple[Set[str], float]] = {}
            for state, keys, t0 in batch:
                keyset, first = merged.get(state, (set(), t0))
                keyset.update(keys)
                merged[state] = (keyset, min(first, t0))
            for state, (keys, t0) in merged.items():
                try:
                    self._process_state(state, keys, t0)
                except Exception:
                    # a poisoned batch must not kill the delivery thread;
                    # affected subs simply see no delta (their shortlist
                    # catches up on the next batch that touches them)
                    continue

    # ------------------------------------------------------------------
    # dirty-batch processing
    # ------------------------------------------------------------------

    def _process_state(self, state: str, keys: Set[str], t0: float) -> None:
        table = self.tables.get(state)
        if table is None:
            return
        dead: List[str] = []
        with self._lock:
            self.batches += 1
            for key in keys:
                for sid in tuple(self._key_index.get((state, key), ())):
                    sub = self._subs.get(sid)
                    if sub is None:
                        continue
                    value = table.get(key)
                    if value is None or value == sub.last_value:
                        continue
                    sub.last_value = value
                    self._emit_locked(sub, value, t0, dead)
            if self._topk_subs.get(state):
                self._process_topk_locked(state, keys, table, t0, dead)
            for sid in dead:
                self._remove_locked(sid)

    def _process_topk_locked(self, state: str, keys: Set[str], table,
                             t0: float, dead: List[str]) -> None:
        handler = self.topk_handlers.get(state)
        index = getattr(handler, "index", None)
        if index is None:
            return
        suffix = getattr(index, "suffix", "-I")
        items = [k[:-len(suffix)] for k in keys
                 if k.endswith(suffix) and not k.startswith("MEAN")]
        if not items:
            return
        sub_ids = self._topk_subs.get(state, ())
        subs = [self._subs[sid] for sid in sub_ids if sid in self._subs]
        total = len(subs)
        if not total:
            return
        cand: Set[str] = set()
        for item in items:
            cand.update(self._member_index.get((state, item), ()))
        self._entrant_candidates_locked(state, items, table, index,
                                        [s for s in subs
                                         if s.sub_id not in cand], cand)
        self.candidates += len(cand)
        self.candidate_total += total
        self._selectivity_gauge(state).set(len(cand) / total)
        if not cand:
            return
        by_k: Dict[int, List[_Subscription]] = {}
        for sid in cand:
            sub = self._subs.get(sid)
            if sub is not None:
                by_k.setdefault(sub.k, []).append(sub)
        for k, group in by_k.items():
            try:
                results = index.topk_many(
                    np.stack([s.vec for s in group]), k)
            except Exception:
                continue  # width-mismatch mid-rebuild: next batch catches up
            self.rescored += len(group)
            self._rescored_counter(state).inc(len(group))
            for sub, res in zip(group, results):
                self._diff_and_emit_locked(sub, res, t0, dead)

    def _entrant_candidates_locked(self, state: str, items: List[str],
                                   table, index,
                                   subs: List[_Subscription],
                                   cand: Set[str]) -> None:
        """Add subs a dirty row could ENTER: one stacked matmul against
        each sub's admission threshold, optionally narrowed by the ANN
        tier's probed-centroid sets."""
        if not subs:
            return
        suffix = getattr(index, "suffix", "-I")
        vecs, kept_items = [], []
        for item in items:
            payload = table.get(f"{item}{suffix}")
            if payload is None:
                continue
            try:
                vec = np.array([t for t in payload.split(";") if t],
                               dtype=np.float32)
            except ValueError:
                continue
            vecs.append(vec)
            kept_items.append(item)
        if not vecs:
            return
        width = len(vecs[0])
        if any(len(v) != width for v in vecs):
            # mixed widths mid-republish: be conservative, take everyone
            cand.update(s.sub_id for s in subs)
            return
        v_mat = np.stack(vecs)  # (n_dirty, d)
        subs = [s for s in subs
                if s.vec is not None and s.vec.shape[0] == width]
        if not subs:
            return
        q_mat = np.stack([s.vec for s in subs])  # (n_subs, d)
        scores = q_mat @ v_mat.T  # (n_subs, n_dirty)
        ann = getattr(index, "_ann", None)
        if ann is not None:
            allowed = self._ann_mask(ann, subs, v_mat)
            if allowed is not None:
                scores = np.where(allowed, scores, _NEG_INF)
        thresholds = np.array([s.threshold for s in subs],
                              dtype=np.float64)
        hits = (scores >= thresholds[:, None]).any(axis=1)
        for sub, hit in zip(subs, hits):
            if hit:
                cand.add(sub.sub_id)

    @staticmethod
    def _ann_mask(ann, subs: List[_Subscription],
                  v_mat: np.ndarray) -> Optional[np.ndarray]:
        """(n_subs, n_dirty) bool: dirty row j's centroid is in sub i's
        probed set.  Exact w.r.t. ANN-served results: an item outside the
        probed lists cannot appear in that sub's top-k, so filtering it
        out of the entrant check loses nothing the query could return."""
        try:
            cents = np.asarray(ann.centroids, dtype=np.float32)
            nprobe = int(getattr(ann, "nprobe", 1))
            if cents.ndim != 2 or cents.shape[1] != v_mat.shape[1]:
                return None
            # IVF assigns rows to centroids by L2, probes by inner
            # product (serve/ann.py) — mirror both exactly
            cnorm = np.sum(cents * cents, axis=1)
            assign = np.argmin(cnorm[None, :] - 2.0 * (v_mat @ cents.T),
                               axis=1)  # (n_dirty,)
            token = id(ann)
            allowed = np.zeros((len(subs), v_mat.shape[0]), dtype=bool)
            for i, sub in enumerate(subs):
                cache = sub.probe_cache
                if cache is None or cache[0] != token:
                    ip = sub.vec @ cents.T
                    n = min(nprobe, ip.shape[0])
                    probed = set(
                        np.argpartition(-ip, n - 1)[:n].tolist())
                    sub.probe_cache = (token, probed)
                    cache = sub.probe_cache
                probed = cache[1]
                for j, c in enumerate(assign):
                    if int(c) in probed:
                        allowed[i, j] = True
            return allowed
        except Exception:
            return None  # narrowing is an optimization, never a gate

    def _diff_and_emit_locked(self, sub: _Subscription, res, t0: float,
                              dead: List[str]) -> None:
        new = {item: float(score) for item, score in res}
        old = sub.scores
        eps = self.score_eps
        ups = [f"+{item}:{score}" for item, score in res
               if item not in old or (abs(old[item] - float(score)) > eps
                                      if eps else old[item] != float(score))]
        downs = [f"-{item}" for item in old if item not in new]
        state = sub.state
        for item in new:
            if item not in old:
                self._member_index.setdefault(
                    (state, item), set()).add(sub.sub_id)
        for item in old:
            if item not in new:
                members = self._member_index.get((state, item))
                if members is not None:
                    members.discard(sub.sub_id)
                    if not members:
                        del self._member_index[(state, item)]
        sub.scores = new
        sub.threshold = (min(new.values())
                         if len(new) >= sub.k and new else _NEG_INF)
        if not ups and not downs:
            return
        self._emit_locked(sub, ";".join(ups + downs), t0, dead)

    def _emit_locked(self, sub: _Subscription, payload: str, t0: float,
                     dead: List[str]) -> None:
        sub.seq += 1
        sub.ring.append((sub.seq, payload))
        while len(sub.ring) > self.ring_cap:
            sub.ring.popleft()
            self._obs_ring_evictions.inc()
        self.deltas += 1
        self._delta_counter(sub.state, sub.kind).inc()
        self._latency_hist(sub.state).observe(time.perf_counter() - t0)
        try:
            sub.sink.send_push(format_push(sub.sub_id, sub.seq, payload))
        except Exception:
            dead.append(sub.sub_id)

    # ------------------------------------------------------------------
    # verbs
    # ------------------------------------------------------------------

    def subscribe(self, state: str, kind: str, arg: str, k: int,
                  sink) -> Tuple[str, int, str]:
        """-> (sub_id, baseline_seq, snapshot).  Raises PushError on
        anything the server should answer with an E line."""
        self.watch_table(state)
        with self._lock:
            return self._subscribe_locked(state, kind, arg, k, sink)

    def _subscribe_locked(self, state: str, kind: str, arg: str, k: int,
                          sink) -> Tuple[str, int, str]:
        if len(self._subs) >= self.max_subs:
            raise PushError("too many subscriptions")
        table = self.tables.get(state)
        if table is None:
            raise PushError(f"unknown state: {state}")
        sub_id = f"{self.epoch}-{self._next_n}"
        sub = _Subscription(sub_id, state, kind, arg, k, sink)
        if kind == KIND_KEY:
            sub.last_value = table.get(arg)
            snapshot = sub.last_value or ""
            self._key_index.setdefault((state, arg), set()).add(sub_id)
        elif kind == KIND_TOPK:
            handler = self.topk_handlers.get(state)
            index = getattr(handler, "index", None)
            if index is None:
                raise PushError(f"no topk index for state: {state}")
            if k < 1:
                raise PushError("k must be >= 1")
            try:
                sub.vec = np.array([t for t in arg.split(";") if t],
                                   dtype=np.float32)
                res = index.topk(sub.vec, k)
            except Exception as e:
                raise PushError(f"bad topk subscription: {e}")
            sub.scores = {item: float(score) for item, score in res}
            sub.threshold = (min(sub.scores.values())
                             if len(sub.scores) >= k and sub.scores
                             else _NEG_INF)
            snapshot = ";".join(f"{item}:{score}" for item, score in res)
            for item in sub.scores:
                self._member_index.setdefault(
                    (state, item), set()).add(sub_id)
            self._topk_subs.setdefault(state, set()).add(sub_id)
        else:
            raise PushError(f"bad subscription kind: {kind}")
        # arm BEFORE the sub becomes visible: deltas raced in by the
        # worker queue behind the pending S reply instead of overtaking it
        sink.arm()
        self._next_n += 1
        self._subs[sub_id] = sub
        self._has_subs = True
        self._subs_gauge(state, kind).inc(1)
        return sub_id, 0, snapshot

    def resume(self, state: str, kind: str, arg: str, k: int, cursor: str,
               sink):
        """-> ("replay", sub_id, from_seq, None) with the missed deltas
        deferred onto the sink, or ("snapshot", new_sub_id, 0, snapshot)
        when the ring cannot bridge (fresh epoch — see module doc)."""
        sub_id, sep, seq_s = cursor.rpartition(":")
        if not sep or not sub_id:
            raise PushError("bad resume cursor")
        try:
            cursor_seq = int(seq_s)
        except ValueError:
            raise PushError("bad resume cursor")
        self.watch_table(state)
        with self._lock:
            sub = self._subs.get(sub_id)
            if (sub is not None and sub.state == state
                    and sub.kind == kind and sub.arg == arg
                    and sub.k == k and cursor_seq <= sub.seq):
                ring_lo = sub.ring[0][0] if sub.ring else sub.seq + 1
                if cursor_seq >= ring_lo - 1:
                    sub.sink = sink
                    sink.arm()
                    sink.defer([format_push(sub_id, s, p)
                                for s, p in sub.ring if s > cursor_seq])
                    self._obs_resume["replay"].inc()
                    return ("replay", sub_id, cursor_seq, None)
            # ring miss / unknown id / spec mismatch: fresh subscription
            new_id, seq, snapshot = self._subscribe_locked(
                state, kind, arg, k, sink)
            self._obs_resume["snapshot"].inc()
            return ("snapshot", new_id, seq, snapshot)

    def unsubscribe(self, sub_id: str) -> bool:
        with self._lock:
            if sub_id not in self._subs:
                return False
            self._remove_locked(sub_id)
            return True

    def drop_sink(self, sink) -> int:
        """Remove every subscription bound to a (closed) connection."""
        with self._lock:
            doomed = [sid for sid, sub in self._subs.items()
                      if sub.sink is sink]
            for sid in doomed:
                self._remove_locked(sid)
            return len(doomed)

    def _remove_locked(self, sub_id: str) -> None:
        sub = self._subs.pop(sub_id, None)
        if sub is None:
            return
        state = sub.state
        if sub.kind == KIND_KEY:
            bucket = self._key_index.get((state, sub.arg))
            if bucket is not None:
                bucket.discard(sub_id)
                if not bucket:
                    del self._key_index[(state, sub.arg)]
        else:
            for item in sub.scores:
                members = self._member_index.get((state, item))
                if members is not None:
                    members.discard(sub_id)
                    if not members:
                        del self._member_index[(state, item)]
            bucket = self._topk_subs.get(state)
            if bucket is not None:
                bucket.discard(sub_id)
                if not bucket:
                    del self._topk_subs[state]
        self._has_subs = bool(self._subs)
        self._subs_gauge(state, sub.kind).inc(-1)

    def subscription_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def close(self) -> None:
        with self._dirty_cond:
            self._closed = True
            self._dirty_cond.notify_all()
        self._thread.join(timeout=5)

    # ------------------------------------------------------------------
    # instruments (lazy per-label caches, obs/metrics.py contract)
    # ------------------------------------------------------------------

    def _delta_counter(self, state: str, kind: str):
        c = self._obs_deltas.get((state, kind))
        if c is None:
            c = obs_metrics.get_registry().counter(
                "tpums_push_deltas_total", state=state, kind=kind)
            self._obs_deltas[(state, kind)] = c
        return c

    def _latency_hist(self, state: str):
        h = self._obs_latency.get(state)
        if h is None:
            h = obs_metrics.get_registry().histogram(
                "tpums_push_latency_seconds", state=state)
            self._obs_latency[state] = h
        return h

    def _subs_gauge(self, state: str, kind: str):
        g = self._obs_subs.get((state, kind))
        if g is None:
            g = obs_metrics.get_registry().gauge(
                "tpums_push_subs_active", state=state, kind=kind)
            self._obs_subs[(state, kind)] = g
        return g

    def _rescored_counter(self, state: str):
        c = self._obs_rescored.get(state)
        if c is None:
            c = obs_metrics.get_registry().counter(
                "tpums_push_rescored_total", state=state)
            self._obs_rescored[state] = c
        return c

    def _selectivity_gauge(self, state: str):
        g = self._obs_selectivity.get(state)
        if g is None:
            g = obs_metrics.get_registry().gauge(
                "tpums_push_selectivity", state=state)
            self._obs_selectivity[state] = g
        return g


# ---------------------------------------------------------------------------
# delivery audit (the PR-9 tiling idea applied to subscription streams)
# ---------------------------------------------------------------------------

def audit_push_sequences(events: Sequence[Tuple[str, str, int]],
                         tiles: int = 8) -> dict:
    """Zero-miss/zero-dup audit over client-observed push streams.

    ``events`` is every subscription-bearing frame a client (or many
    clients, concatenated) received, in arrival order per subscription:
    ``("S", sub_id, seq)`` for a snapshot baseline (SUBSCRIBE reply or a
    RESUME snapshot fallback — a fresh id starts a fresh stream),
    ``("S", sub_id, from_seq)`` for a RESUME replay acknowledgment (the
    R line: the stream resumes AFTER from_seq), and ``("P", sub_id,
    seq)`` for every delta.  Per subscription the P sequence must be
    strictly contiguous from its latest baseline: a hole counts into
    ``missed``, a repeat into ``duplicates``.

    Like ``update_plane.audit_partitions``, results are tiled —
    subscriptions hash into ``tiles`` buckets so a localized failure
    (one replica's sequence space) shows up as hot tiles rather than a
    fleet-wide smear."""
    if tiles < 1:
        raise ValueError("tiles must be >= 1")
    tile_stats = [{"subs": 0, "delivered": 0, "missed": 0,
                   "duplicates": 0} for _ in range(tiles)]
    expected: Dict[str, int] = {}
    for kind, sub_id, seq in events:
        t = zlib.crc32(sub_id.encode("utf-8")) % tiles
        if sub_id not in expected:
            tile_stats[t]["subs"] += 1
        if kind == "S":
            expected[sub_id] = int(seq) + 1
            continue
        if kind != "P":
            raise ValueError(f"bad audit event kind: {kind!r}")
        seq = int(seq)
        exp = expected.get(sub_id)
        if exp is None:
            # a delta with no baseline: everything before it is missing
            tile_stats[t]["missed"] += max(seq - 1, 0)
            tile_stats[t]["delivered"] += 1
            expected[sub_id] = seq + 1
        elif seq == exp:
            tile_stats[t]["delivered"] += 1
            expected[sub_id] = seq + 1
        elif seq > exp:
            tile_stats[t]["missed"] += seq - exp
            tile_stats[t]["delivered"] += 1
            expected[sub_id] = seq + 1
        else:
            tile_stats[t]["duplicates"] += 1
    out = {"subs": sum(ts["subs"] for ts in tile_stats),
           "delivered": sum(ts["delivered"] for ts in tile_stats),
           "missed": sum(ts["missed"] for ts in tile_stats),
           "duplicates": sum(ts["duplicates"] for ts in tile_stats),
           "tiles": tile_stats}
    return out
