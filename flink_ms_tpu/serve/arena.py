"""Zero-copy shared-memory arena — ONE factor store shared by the three
planes (ROADMAP item 1).

The dict-backed ``ModelTable`` keeps the model private to the consumer
process: the C++ lookup server needs its own store fed row-by-row over a
socket/FFI, the SGD update plane round-trips freshness through the
journal reader, and snapshot/geo publish is an O(state) *serialize*.
The arena collapses those copies: a single mmap'd file holds fixed-
stride factor slabs addressed by an open-addressing key index, the
consumer's ingest path writes rows in place, and every reader — the C++
epoll server (``native/arena.cpp``), co-located update workers, the
snapshotter, the geo replicator — maps the same pages.

File layout (little-endian throughout)::

    <dir>/CURRENT                 name of the live generation file
    <dir>/writer.lock             flock'd by THE writer (kernel-released)
    <dir>/arena-<gen>.dat:
        [0:64)   header: magic "TPMA" | version u32 | capacity u64 |
                 stride u32 | key_cap u32 | count u64 | generation u64 |
                 retired u32 | pad u32 | mutations u64
        [64:..)  capacity slots of ceil8(12 + key_cap + stride) bytes:
                 seq u32 | klen u32 | vlen u32 | key[key_cap] |
                 value[stride]

The slot array IS the index: a key hashes (32-bit FNV-1a, the same
``table._fnv1a`` that routes shards everywhere else) to ``h % capacity``
and linear-probes from there.  Model tables only ever upsert (last-
writer-wins, no deletes), so probe chains are stable and an EMPTY slot
(``seq == 0``) terminates a lookup.

Seqlock protocol (readers are lock-free; one writer, flock-excluded):

    writer: seq -> odd, write klen/vlen/key/value, seq -> even
    reader: s1 = load(seq); if 0 -> chain end (missing); if odd ->
            bounded retry then missing; copy row; s2 = load(seq);
            s1 != s2 -> torn, retry the slot (bounded), count the retry

A writer SIGKILLed mid-row leaves that slot's seq odd forever: readers
report the key missing — never a torn value — and the respawned
consumer's at-least-once journal replay rewrites the row (even seq),
repairing it.  Ordering relies on the x86-TSO store order the CPython
writer emits through mmap slice stores; the native reader pairs it with
acquire loads (``native/arena.cpp``).

Growth (load factor, oversize value/key) builds generation g+1, rehashes
live rows, repoints CURRENT, then sets the old header's ``retired`` flag
— attached readers see the flag on their next lookup and remap through
CURRENT (``tpums_arena_refresh``).

Batched writes go through the native plane when the toolchain is
available: ``put_many_columns`` encodes the whole batch into contiguous
columnar blobs OUTSIDE the table lock, then hands them to the C++
``tpums_arena_put_batch`` (``native/arena.cpp``) — one FFI call and zero
Python bytecode per row, byte-parity-exact with ``put_bytes``.  Growth
falls back to the Python path for the blocking row, then resumes
natively.  ``cas_many_columns`` is the update plane's in-place
compare-and-swap (``tpums_arena_cas_floats``): same seqlock discipline,
value drift reported back for an LWW re-put instead of clobbered.

Knobs: ``TPUMS_ARENA_CAPACITY`` (slots, default 65536),
``TPUMS_ARENA_STRIDE`` (max value bytes, default 256),
``TPUMS_ARENA_KEYCAP`` (max key bytes, default 48),
``TPUMS_ARENA_BATCH=0`` (disable the native batch writer),
``TPUMS_ARENA_CAS=0`` (update plane re-puts rows instead of CAS),
``TPUMS_ARENA_PREFAULT=1`` (bulk-populate the writer mapping at attach
— bootstrap replay then never stalls on first-touch faults);
selection is ``--table arena`` / ``TPUMS_TABLE=arena`` on the consumer
CLI (the default for sharded/HA/elastic fleets — ``TPUMS_TABLE=dict``
opts out).
"""

from __future__ import annotations

import ctypes
import errno
import json
import mmap
import os
import struct
import threading
import time
from typing import Iterator, List, Optional, Sequence, Tuple

from .table import _fnv1a, _fnv1a_batch

try:  # SIMD newline guard for the columnar blobs (bytes.count restarts
    # memchr at every match — ~1 GB/s on 100-byte rows; the vectorized
    # compare-and-sum runs at memory bandwidth)
    import numpy as _np
except Exception:  # pragma: no cover - numpy ships with the jax stack
    _np = None


def _nl_count(b: bytes) -> int:
    if _np is not None and len(b) >= 4096:
        return int((_np.frombuffer(b, _np.uint8) == 10).sum())
    return b.count(b"\n")


MAGIC = b"TPMA"
VERSION = 1
HEADER_SIZE = 64
SLOT_HDR = 12  # seq u32 | klen u32 | vlen u32
CURRENT = "CURRENT"
WRITER_LOCK = "writer.lock"
# bounded seqlock retries: past this the writer is dead mid-row (odd) or
# the slot is being rewritten faster than we can copy it (never at our
# write rates) — report missing, journal replay repairs
MAX_SEQ_RETRIES = 64

_HDR = struct.Struct("<4sIQIIQQI")  # through `retired`; rest reserved


def _env_int(name: str, default: int, lo: int) -> int:
    try:
        return max(int(os.environ.get(name, default)), lo)
    except ValueError:
        return default


def default_capacity() -> int:
    return _env_int("TPUMS_ARENA_CAPACITY", 1 << 16, 64)


def default_stride() -> int:
    return _env_int("TPUMS_ARENA_STRIDE", 256, 16)


def default_key_cap() -> int:
    return _env_int("TPUMS_ARENA_KEYCAP", 48, 8)


def slot_size(key_cap: int, stride: int) -> int:
    return (SLOT_HDR + key_cap + stride + 7) & ~7


def gen_filename(generation: int) -> str:
    return f"arena-{generation:08d}.dat"


class ArenaBusy(RuntimeError):
    """Another live process holds this arena's writer flock."""


class Arena:
    """One mapped generation file.  ``writable`` attaches the mapping
    read-write (the single writer); readers map shared read-only."""

    def __init__(self, path: str, writable: bool):
        self.path = path
        self.writable = writable
        fd = os.open(path, os.O_RDWR if writable else os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            self.mm = mmap.mmap(
                fd, size,
                prot=(mmap.PROT_READ | mmap.PROT_WRITE) if writable
                else mmap.PROT_READ)
        finally:
            os.close(fd)
        (magic, version, self.capacity, self.stride, self.key_cap,
         _count, self.generation, _retired) = _HDR.unpack_from(self.mm, 0)
        if magic != MAGIC or version != VERSION:
            self.mm.close()
            raise ValueError(f"{path}: not a tpums arena (magic/version)")
        self.slot_size = slot_size(self.key_cap, self.stride)
        if size < HEADER_SIZE + self.capacity * self.slot_size:
            # a truncated copy (torn snapshot ship) must fail structurally
            # here, not as an out-of-bounds read mid-scan
            self.mm.close()
            raise ValueError(
                f"{path}: short arena file ({size} bytes for capacity "
                f"{self.capacity})")
        if writable and os.environ.get("TPUMS_ARENA_PREFAULT") == "1":
            # bulk-populate the mapping at attach time: hash-distributed
            # inserts otherwise take a first-touch fault on nearly every
            # row, and prefetch can't hide a fault the way it hides a
            # cache miss.  One kernel pass here is far cheaper than a
            # million faults during bootstrap replay.
            try:
                self.mm.madvise(getattr(mmap, "MADV_POPULATE_WRITE", 23))
            except (AttributeError, OSError, ValueError):
                pass  # kernel < 5.14: faults amortize as before

    # -- header fields (count/retired are live, re-read per call) ---------

    @property
    def count(self) -> int:
        return struct.unpack_from("<Q", self.mm, 24)[0]

    def _set_count(self, n: int) -> None:
        struct.pack_into("<Q", self.mm, 24, n)

    @property
    def mutations(self) -> int:
        """Writer-bumped change counter: in-place updates move neither
        ``count`` nor the file size, so index-staleness checks (top-k/DOT
        version probes via ``tpums_log_bytes``) read this instead."""
        return struct.unpack_from("<Q", self.mm, 48)[0]

    def _bump_mutations(self) -> None:
        struct.pack_into("<Q", self.mm, 48,
                         (self.mutations + 1) & 0xFFFFFFFFFFFFFFFF)

    @property
    def retired(self) -> bool:
        return struct.unpack_from("<I", self.mm, 40)[0] != 0

    def retire(self) -> None:
        struct.pack_into("<I", self.mm, 40, 1)

    @property
    def size_bytes(self) -> int:
        return HEADER_SIZE + self.capacity * self.slot_size

    def resident_bytes(self) -> int:
        """Pages actually allocated (the file is sparse until written)."""
        try:
            return os.stat(self.path).st_blocks * 512
        except OSError:
            return 0

    # -- creation ---------------------------------------------------------

    @classmethod
    def create(cls, path: str, capacity: int, stride: int, key_cap: int,
               generation: int) -> "Arena":
        size = HEADER_SIZE + capacity * slot_size(key_cap, stride)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.ftruncate(fd, size)
            hdr = bytearray(HEADER_SIZE)
            _HDR.pack_into(hdr, 0, MAGIC, VERSION, capacity, stride,
                           key_cap, 0, generation, 0)
            os.pwrite(fd, bytes(hdr), 0)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.rename(tmp, path)
        return cls(path, writable=True)

    # -- seqlock row access ----------------------------------------------

    def _slot_off(self, idx: int) -> int:
        return HEADER_SIZE + idx * self.slot_size

    def _read_slot(self, off: int) -> Optional[Tuple[bytes, bytes]]:
        """Seqlock-read one slot -> (key, value) bytes, or None when the
        slot is EMPTY, mid-write (odd), or torn past the retry bound.
        The caller distinguishes empty via ``peek_seq``."""
        mm = self.mm
        for _ in range(MAX_SEQ_RETRIES):
            s1 = struct.unpack_from("<I", mm, off)[0]
            if s1 == 0:
                return None
            if s1 & 1:
                _RETRIES.inc()
                continue
            klen, vlen = struct.unpack_from("<II", mm, off + 4)
            if klen > self.key_cap or vlen > self.stride:
                return None  # torn mid-claim on a pre-TSO arch; never LWW
            key = mm[off + SLOT_HDR:off + SLOT_HDR + klen]
            val = mm[off + SLOT_HDR + self.key_cap:
                     off + SLOT_HDR + self.key_cap + vlen]
            s2 = struct.unpack_from("<I", mm, off)[0]
            if s1 == s2:
                return key, val
            _RETRIES.inc()
        return None

    def peek_seq(self, idx: int) -> int:
        return struct.unpack_from("<I", self.mm, self._slot_off(idx))[0]

    def get(self, key: str) -> Optional[str]:
        kb = key.encode("utf-8")
        return self.get_bytes(kb)

    def get_bytes(self, kb: bytes) -> Optional[str]:
        if len(kb) > self.key_cap:
            return None
        cap = self.capacity
        idx = _fnv1a_bytes(kb) % cap
        for _ in range(cap):
            off = self._slot_off(idx)
            seq = struct.unpack_from("<I", self.mm, off)[0]
            if seq == 0:
                return None  # chain end
            row = self._read_slot(off)
            if row is not None and row[0] == kb:
                return row[1].decode("utf-8")
            if row is None and not (seq & 1) and seq != 0:
                pass  # torn even-seq read: fall through and keep probing
            idx = idx + 1
            if idx == cap:
                idx = 0
        return None

    # -- writer side ------------------------------------------------------

    def put_bytes(self, kb: bytes, vb: bytes, h: Optional[int] = None
                  ) -> bool:
        """Upsert one row in place; False when the arena must grow
        (oversize key/value or load factor ceiling).  Caller holds the
        table lock — there is exactly one writer."""
        if len(kb) > self.key_cap or len(vb) > self.stride:
            return False
        cap = self.capacity
        idx = (_fnv1a_bytes(kb) if h is None else h) % cap
        mm = self.mm
        for _ in range(cap):
            off = self._slot_off(idx)
            seq, klen = struct.unpack_from("<II", mm, off)
            if seq == 0 and klen == 0:
                n = self.count
                if n + 1 > (cap - (cap >> 3)):  # keep 1/8 headroom
                    return False
                # claim: odd seq first so a concurrent reader never
                # trusts the half-written key/value bytes
                struct.pack_into("<I", mm, off, 1)
                kc = self.key_cap
                mm[off + SLOT_HDR:off + SLOT_HDR + len(kb)] = kb
                mm[off + SLOT_HDR + kc:off + SLOT_HDR + kc + len(vb)] = vb
                struct.pack_into("<II", mm, off + 4, len(kb), len(vb))
                struct.pack_into("<I", mm, off, 2)
                self._set_count(n + 1)
                self._bump_mutations()
                return True
            if (klen == len(kb)
                    and mm[off + SLOT_HDR:off + SLOT_HDR + klen] == kb):
                # in-place update: key is immutable after the claim, only
                # vlen + value move under the odd window
                struct.pack_into("<I", mm, off, seq | 1)
                kc = self.key_cap
                mm[off + SLOT_HDR + kc:off + SLOT_HDR + kc + len(vb)] = vb
                struct.pack_into("<I", mm, off + 8, len(vb))
                struct.pack_into("<I", mm, off, (seq | 1) + 1)
                self._bump_mutations()
                return True
            idx = idx + 1
            if idx == cap:
                idx = 0
        return False

    def items(self) -> Iterator[Tuple[str, str]]:
        """Seqlock-scan every claimed slot.  Rows written during the scan
        may or may not appear (same contract as dict-table ``items`` on a
        copied shard); odd-stuck rows are skipped."""
        for idx in range(self.capacity):
            if self.peek_seq(idx) == 0:
                continue
            row = self._read_slot(self._slot_off(idx))
            if row is not None:
                yield row[0].decode("utf-8"), row[1].decode("utf-8")

    def flush(self) -> None:
        if self.writable:
            self.mm.flush()

    def occupied_runs(self) -> Iterator[Tuple[int, int]]:
        """Yield ``(first_slot, last_slot_exclusive)`` runs of claimed
        slots — the occupancy map behind the sparse publish copy.  A
        numpy strided view over the mapping finds run edges in O(cap/8)
        memory; the struct fallback scans slot headers one by one."""
        ss = self.slot_size
        try:
            import numpy as np

            n_words = self.capacity * ss // 4
            seqs = np.frombuffer(self.mm, dtype=np.uint32, count=n_words,
                                 offset=HEADER_SIZE)[::ss // 4]
            occ = (seqs != 0).view(np.int8)
            edges = np.flatnonzero(np.diff(
                np.concatenate((np.int8([0]), occ, np.int8([0])))))
            for s, e in zip(edges[0::2].tolist(), edges[1::2].tolist()):
                yield s, e
            return
        except ImportError:
            pass
        start = None
        for idx in range(self.capacity):
            if self.peek_seq(idx) != 0:
                if start is None:
                    start = idx
            elif start is not None:
                yield start, idx
                start = None
        if start is not None:
            yield start, self.capacity

    def sparse_copy_to(self, dst_path: str) -> int:
        """Copy this arena to ``dst_path`` writing ONLY the header and
        occupied slot runs — empty slots become holes, so bytes copied
        track rows, not capacity.  Offsets are preserved (holes read as
        zeros = empty slots), so the result is a valid arena file.
        FICLONE is tried first: on reflink filesystems the whole publish
        is one O(1) ioctl.  Returns bytes actually written (== logical
        size after a reflink).  Caller quiesces the writer; durability
        (fsync) is the caller's."""
        size = HEADER_SIZE + self.capacity * self.slot_size
        dfd = os.open(dst_path, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            sfd = os.open(self.path, os.O_RDONLY)
            try:
                import fcntl

                fcntl.ioctl(dfd, _FICLONE, sfd)
                return size
            except OSError:
                pass  # not a reflink fs — sparse slot-run copy below
            finally:
                os.close(sfd)
            os.ftruncate(dfd, size)
            written = os.pwrite(dfd, self.mm[:HEADER_SIZE], 0)
            ss = self.slot_size
            chunk_slots = max((8 << 20) // ss, 1)
            # coalesce runs whose gap is below one syscall's worth of
            # bytes: scattered hash occupancy (runs of ~1/(1-load) slots)
            # must degrade to a few big sequential writes, not a pwrite
            # per probe-chain fragment
            merge_gap = max((64 << 10) // ss, 1)
            for s, e in self._merged_runs(merge_gap):
                while s < e:
                    run = min(e - s, chunk_slots)
                    off = HEADER_SIZE + s * ss
                    written += os.pwrite(
                        dfd, self.mm[off:off + run * ss], off)
                    s += run
            return written
        finally:
            os.close(dfd)

    def _merged_runs(self, max_gap_slots: int) -> Iterator[Tuple[int, int]]:
        cur = None
        for s, e in self.occupied_runs():
            if cur is None:
                cur = (s, e)
            elif s - cur[1] <= max_gap_slots:
                cur = (cur[0], e)
            else:
                yield cur
                cur = (s, e)
        if cur is not None:
            yield cur

    def link_to(self, dst_path: str) -> int:
        """O(1) publish: hardlink this generation's inode at ``dst_path``.
        The artifact SHARES the live mapping — in-place updates after
        publish are visible in it, which is sound for this upsert-only
        LWW table (restore + journal replay from the manifest offset
        rewrites every row the journal touched after the offset, so
        at-publish and newer-than-publish row values converge to the
        same head state).  Falls back to a sparse copy across
        filesystems.  Returns bytes newly written (0 for a link)."""
        try:
            os.link(self.path, dst_path)
            return 0
        except OSError as e:
            if e.errno not in (errno.EXDEV, errno.EPERM, errno.EMLINK):
                raise
            return self.sparse_copy_to(dst_path)

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass  # a reader still holds a buffer; refcount closes it


def _fnv1a_bytes(b: bytes) -> int:
    h = 0x811C9DC5
    for ch in b:
        h ^= ch
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


# -- metrics (module-level: readers are lock-free, the counter is shared) --

class _LazyCounter:
    """Defer the obs registry import so arena readers work in contexts
    that never touch observability (e.g. the snapshot loader)."""

    def __init__(self, name: str):
        self._name = name
        self._c = None

    def inc(self, n: int = 1) -> None:
        if self._c is None:
            from ..obs.metrics import get_registry

            self._c = get_registry().counter(self._name)
        self._c.inc(n)


class _LazyHistogram:
    """Same deferred-registry trick as ``_LazyCounter`` for histograms."""

    def __init__(self, name: str):
        self._name = name
        self._h = None

    def observe(self, v: float) -> None:
        if self._h is None:
            from ..obs.metrics import get_registry

            self._h = get_registry().histogram(self._name)
        self._h.observe(v)


_RETRIES = _LazyCounter("tpums_arena_read_retries_total")
# write-plane counters: batch rows/seconds through the native writer and
# CAS outcomes; the C++ writer mirrors them into the <dir>/writer.stats
# sidecar so the native METRICS verb exports the same names from server
# processes that never run Python on the write path
_BATCH_ROWS = _LazyCounter("tpums_arena_batch_rows_total")
_BATCH_SECONDS = _LazyCounter("tpums_arena_batch_put_seconds_total")
_BATCH_HIST = _LazyHistogram("tpums_arena_batch_put_seconds")
_CAS_SUCCESS = _LazyCounter("tpums_arena_cas_success_total")
_CAS_RETRY = _LazyCounter("tpums_arena_cas_retry_total")


# -- directory-level open/create ------------------------------------------

def current_path(dir_: str) -> Optional[str]:
    try:
        with open(os.path.join(dir_, CURRENT)) as f:
            name = f.read().strip()
    except OSError:
        return None
    return os.path.join(dir_, name) if name else None


def _write_current(dir_: str, name: str) -> None:
    tmp = os.path.join(dir_, f".{CURRENT}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dir_, CURRENT))


def attach_reader(dir_: str) -> Optional[Arena]:
    """Map the live generation read-only, or None when no arena exists."""
    path = current_path(dir_)
    if path is None or not os.path.exists(path):
        return None
    return Arena(path, writable=False)


# -- hole-aware clone (snapshot publish + geo shipping) --------------------

_FICLONE = 0x40049409  # linux ioctl: reflink the whole file (btrfs/xfs)


def clone_file(src: str, dst: str, do_fsync: bool = True) -> int:
    """Copy ``src`` to ``dst`` O(resident-data): reflink when the
    filesystem supports it (O(1)), else ``copy_file_range`` over the
    SEEK_DATA extents so the arena's unwritten slots (file holes) cost
    nothing.  Returns the logical size.  The destination is sized first
    so holes stay holes.  ``do_fsync=False`` leaves durability to the
    caller (``quiesce_copy`` fsyncs AFTER releasing the writer lock so
    ingest stalls only for the in-cache copy, not the disk flush)."""
    size = os.stat(src).st_size
    sfd = os.open(src, os.O_RDONLY)
    try:
        dfd = os.open(dst, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            try:
                import fcntl

                fcntl.ioctl(dfd, _FICLONE, sfd)
                return size
            except OSError:
                pass  # not a reflink fs — extent copy below
            os.ftruncate(dfd, size)
            off = 0
            while off < size:
                try:
                    data_start = os.lseek(sfd, off, os.SEEK_DATA)
                except OSError as e:
                    if e.errno == errno.ENXIO:
                        break  # trailing hole
                    raise
                hole = os.lseek(sfd, data_start, os.SEEK_HOLE)
                pos = data_start
                while pos < hole:
                    try:
                        n = os.copy_file_range(sfd, dfd, hole - pos,
                                               offset_src=pos,
                                               offset_dst=pos)
                    except OSError:
                        os.lseek(sfd, pos, os.SEEK_SET)
                        chunk = os.read(sfd, min(hole - pos, 1 << 22))
                        n = os.pwrite(dfd, chunk, pos)
                    if n <= 0:
                        raise OSError(f"short copy at {pos} of {src}")
                    pos += n
                off = hole
            if do_fsync:
                os.fsync(dfd)
            return size
        finally:
            os.close(dfd)
    finally:
        os.close(sfd)


def iter_arena_file(path: str) -> Iterator[Tuple[str, str]]:
    """Row iterator over a standalone arena file (snapshot restore into
    ANY table kind — the portable read side of the O(state) publish)."""
    a = Arena(path, writable=False)
    try:
        yield from a.items()
    finally:
        a.close()


# -- the table ------------------------------------------------------------

class ArenaModelTable:
    """Drop-in for ``serve.table.ModelTable`` backed by the shared arena.

    Same surface (put/put_many/put_many_columns/get/items/len, version +
    puts counters, change listeners, TSV checkpoint snapshot/restore) so
    every consumer of the table contract — top-k index, DOT index, the
    Python lookup server, MemoryStateBackend checkpoints — runs
    unchanged; what changes is WHERE rows live: one mmap'd file the C++
    server and the snapshotter read without a single per-row push."""

    kind = "arena"

    def __init__(self, n_shards: int = 8, dir: Optional[str] = None,
                 capacity: Optional[int] = None,
                 stride: Optional[int] = None,
                 key_cap: Optional[int] = None,
                 publish_mode: Optional[str] = None):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards  # shard_of() parity for routing callers
        self.publish_mode = publish_mode or \
            os.environ.get("TPUMS_ARENA_PUBLISH", "copy")
        if self.publish_mode not in ("copy", "link"):
            raise ValueError("publish_mode must be copy|link")
        self.dir = dir or os.environ.get("TPUMS_ARENA_DIR") or \
            os.path.join(os.getcwd(), "arena")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.RLock()
        self.puts = 0
        self.version = 0
        self._listeners: List = []
        self._batch_listeners: List = []
        self._lock_fd = self._acquire_writer_lock(self.dir)
        # Observed row-size maxima drive the adaptive geometry in _grow.
        # Fresh arenas start at 0; attaching to an existing file seeds
        # them from its geometry (its rows are unscanned — never shrink
        # slabs below what might already be stored).
        self._max_klen = 0
        self._max_vlen = 0
        cur = current_path(self.dir)
        if cur is not None and os.path.exists(cur):
            self.arena = Arena(cur, writable=True)
            self._max_klen = self.arena.key_cap
            self._max_vlen = self.arena.stride
        else:
            self.arena = Arena.create(
                os.path.join(self.dir, gen_filename(0)),
                capacity or default_capacity(),
                stride or default_stride(),
                key_cap or default_key_cap(), 0)
            _write_current(self.dir, gen_filename(0))
        # Native batch writer (native/arena.cpp tpums_arena_put_batch):
        # maps the SAME generation file read-write and applies whole
        # columnar batches with zero Python bytecode per row.  Optional —
        # no toolchain (or TPUMS_ARENA_BATCH=0) leaves the pure-Python
        # path serving every write.  Reopened after every growth flip.
        self._writer_h: Optional[int] = None
        self._writer_lib = None
        self._native_batch = \
            os.environ.get("TPUMS_ARENA_BATCH", "1") != "0"
        self._reopen_native_writer()
        self._last_gauge_ts = 0.0
        self._publish_gauges()

    def _reopen_native_writer(self) -> None:
        """(Re)attach the C++ batch writer to the live generation file.
        Any failure — no compiler, stale lib without the writer ABI —
        degrades silently to the Python write path."""
        if self._writer_h is not None:
            self._writer_lib.tpums_arena_writer_close(self._writer_h)
            self._writer_h = None
        if not self._native_batch:
            return
        try:
            from .native_store import _load_lib

            lib = _load_lib()
            h = lib.tpums_arena_writer_open(
                self.arena.path.encode("utf-8"), self.dir.encode("utf-8"))
        except Exception:
            return
        if h:
            self._writer_lib = lib
            self._writer_h = h

    @staticmethod
    def _acquire_writer_lock(dir_: str) -> int:
        import fcntl

        fd = os.open(os.path.join(dir_, WRITER_LOCK),
                     os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            raise ArenaBusy(f"another live writer holds {dir_} "
                            "(flock) — one arena, one writer")
        os.write(fd, f"{os.getpid()}\n".encode())
        return fd

    # -- ModelTable surface ----------------------------------------------

    def add_change_listener(self, fn, batch_fn=None) -> None:
        with self._lock:
            self._listeners.append(fn)
            self._batch_listeners.append(batch_fn)

    def shard_of(self, key: str) -> int:
        return _fnv1a(key) % self.n_shards

    def put(self, key: str, value: str) -> None:
        with self._lock:
            self._put_locked(key.encode("utf-8"), value.encode("utf-8"))
            self.puts += 1
            self.version += 1
            for fn in self._listeners:
                fn(key)
            self._maybe_gauges()

    def put_many(self, pairs) -> None:
        pairs = list(pairs)
        if not pairs:
            return
        self.put_many_columns([k for k, _ in pairs], [v for _, v in pairs])

    def put_many_columns(self, keys, values, hashes=None) -> None:
        n = len(keys)
        if n == 0:
            return
        if not isinstance(keys, list):
            keys = list(keys)
        if not isinstance(values, list):
            values = list(values)
        # ALL encoding happens before the lock: the writer lock bounds
        # reader-visible seqlock windows and publish quiesce time, so it
        # must cover memory stores only — never per-row utf-8 encodes.
        kbuf = vbuf = None
        if self._writer_h is not None:
            kbuf = "\n".join(keys).encode("utf-8")
            vbuf = "\n".join(values).encode("utf-8")
            if (_nl_count(kbuf) != n - 1
                    or _nl_count(vbuf) != n - 1):
                # embedded newline in a row: the columnar framing can't
                # carry it — per-row path below
                kbuf = vbuf = None
        if kbuf is None:
            kbs = [k.encode("utf-8") for k in keys]
            vbs = [v.encode("utf-8") for v in values]
            if hashes is None and n >= 32:
                hashes = _fnv1a_batch(keys)
            hs = hashes.tolist() if hasattr(hashes, "tolist") else hashes
        with self._lock:
            if kbuf is not None:
                self._put_batch_locked(kbuf, vbuf, n)
            elif hs is None:
                for kb, vb in zip(kbs, vbs):
                    self._put_locked(kb, vb)
            else:
                for kb, vb, h in zip(kbs, vbs, hs):
                    self._put_locked(kb, vb, h)
            self.puts += n
            self.version += 1
            self._notify_locked(keys)
            self._maybe_gauges()

    def _put_batch_locked(self, kbuf: bytes, vbuf: bytes, n: int) -> None:
        """Apply a columnar batch through the C++ writer, falling back to
        the Python path for any row that needs growth (which rebuilds the
        file and reopens the native handle), then resuming natively."""
        lib = self._writer_lib
        t0 = time.perf_counter()
        native_rows = 0
        remaining = n
        while remaining > 0:
            mk = ctypes.c_uint32(0)
            mv = ctypes.c_uint32(0)
            applied = int(lib.tpums_arena_put_batch(
                self._writer_h, kbuf, len(kbuf), vbuf, len(vbuf),
                remaining, ctypes.byref(mk), ctypes.byref(mv)))
            if applied < 0:
                raise OSError("tpums_arena_put_batch failed")
            if mk.value > self._max_klen:
                self._max_klen = mk.value
            if mv.value > self._max_vlen:
                self._max_vlen = mv.value
            native_rows += applied
            remaining -= applied
            if remaining == 0:
                break
            # row `applied` needs growth: put it through the Python path
            # (grows + reopens the native writer), resume with the rest
            kbs = kbuf.split(b"\n")
            vbs = vbuf.split(b"\n")
            self._put_locked(kbs[applied], vbs[applied])
            remaining -= 1
            if remaining == 0:
                break
            kbuf = b"\n".join(kbs[applied + 1:])
            vbuf = b"\n".join(vbs[applied + 1:])
            if self._writer_h is None:
                # native writer did not survive the reopen: finish in
                # Python rather than spinning on applied == 0
                for kb, vb in zip(kbs[applied + 1:], vbs[applied + 1:]):
                    self._put_locked(kb, vb)
                remaining = 0
        dt = time.perf_counter() - t0
        if native_rows:
            _BATCH_ROWS.inc(native_rows)
            _BATCH_SECONDS.inc(dt)
            _BATCH_HIST.observe(dt)

    def cas_many_columns(self, keys: Sequence[str],
                         expected: Sequence[Optional[str]],
                         values: Sequence[str]) -> List[int]:
        """In-place compare-and-swap of whole value payloads: row ``i``
        flips to ``values[i]`` iff the stored bytes still equal
        ``expected[i]`` (seqlock odd/even preserved, so concurrent
        readers never see a torn row).  Returns the indices that did NOT
        swap — key missing, value drifted, ``expected[i] is None``, or
        geometry overflow — which the caller repairs with an LWW re-put.
        Swapped rows move puts/version and fire listeners like a put."""
        n = len(keys)
        if n == 0:
            return []
        kbs = [k.encode("utf-8") for k in keys]
        ebs = [e.encode("utf-8") if e is not None else None
               for e in expected]
        vbs = [v.encode("utf-8") for v in values]
        failed: List[int] = []
        swapped: List[str] = []
        retries = 0
        with self._lock:
            lib, h = self._writer_lib, self._writer_h
            for i in range(n):
                eb = ebs[i]
                if eb is None:
                    failed.append(i)
                    continue
                if h is not None:
                    rc = lib.tpums_arena_cas_floats(
                        h, kbs[i], len(kbs[i]), eb, len(eb),
                        vbs[i], len(vbs[i]))
                else:
                    # Python fallback: the table lock already excludes
                    # every other writer, so read-compare-put IS atomic
                    cur = self.arena.get_bytes(kbs[i])
                    if (cur is not None
                            and cur.encode("utf-8") == eb):
                        self._put_locked(kbs[i], vbs[i])
                        rc = 1
                    else:
                        rc = 0
                if rc == 1:
                    swapped.append(keys[i])
                elif rc == 0:
                    retries += 1
                    failed.append(i)
                else:
                    failed.append(i)
            if swapped:
                self.puts += len(swapped)
                self.version += 1
                self._notify_locked(swapped)
                self._maybe_gauges()
        if swapped:
            _CAS_SUCCESS.inc(len(swapped))
        if retries:
            _CAS_RETRY.inc(retries)
        return failed

    def _notify_locked(self, keys) -> None:
        for fn, batch_fn in zip(self._listeners, self._batch_listeners):
            if batch_fn is not None:
                batch_fn(keys)
            else:
                for key in keys:
                    fn(key)

    def get(self, key: str) -> Optional[str]:
        return self.arena.get(key)

    def __len__(self) -> int:
        return self.arena.count

    def items(self) -> Iterator[Tuple[str, str]]:
        return self.arena.items()

    def flush(self) -> None:
        with self._lock:
            self.arena.flush()

    # -- write path + growth ---------------------------------------------

    def _put_locked(self, kb: bytes, vb: bytes,
                    h: Optional[int] = None) -> None:
        if len(kb) > self._max_klen:
            self._max_klen = len(kb)
        if len(vb) > self._max_vlen:
            self._max_vlen = len(vb)
        while not self.arena.put_bytes(kb, vb, h):
            self._grow(len(kb), len(vb))

    def _grow(self, need_klen: int, need_vlen: int) -> None:
        old = self.arena
        cap = old.capacity
        if old.count + 1 > (cap - (cap >> 3)):
            cap *= 2
        # Rehash is the one moment geometry is free to change, so fit the
        # slabs to OBSERVED row sizes (+25% headroom, 8-byte rounded)
        # instead of doubling the defaults: file size — hence publish
        # copy cost — tracks the payload, not the worst-case guess.
        def _fit(observed: int, need: int, floor: int) -> int:
            want = max(need, observed + (observed >> 2), floor)
            return (want + 7) & ~7

        stride = min(old.stride, _fit(self._max_vlen, need_vlen, 16))
        while stride < need_vlen:
            stride *= 2
        key_cap = min(old.key_cap, _fit(self._max_klen, need_klen, 8))
        while key_cap < need_klen:
            key_cap *= 2
        gen = old.generation + 1
        new = Arena.create(os.path.join(self.dir, gen_filename(gen)),
                           cap, stride, key_cap, gen)
        for k, v in old.items():
            if not new.put_bytes(k.encode("utf-8"), v.encode("utf-8")):
                raise RuntimeError("arena grow rehash overflow")
        _write_current(self.dir, gen_filename(gen))
        old.retire()  # attached readers remap through CURRENT
        self.arena = new
        self._reopen_native_writer()  # the old mapping is dead weight now
        try:
            os.unlink(old.path)  # live mappings keep the inode alive
        except OSError:
            pass

    # -- O(state) publish support ----------------------------------------

    def quiesce_copy(self, dst_path: str) -> dict:
        """Materialize the arena at ``dst_path`` with no writer racing it
        (the table lock IS the quiesce) and return the artifact's
        geometry for the snapshot manifest.

        ``publish_mode="copy"`` (default): reflink / sparse slot-run
        copy, zero serialize — a point-in-time immutable artifact.
        ``publish_mode="link"``: one hardlink, O(1) at ANY row count —
        the artifact shares the live inode, so rows mutated after
        publish show their newer values; sound here because restore
        always replays the journal from the manifest offset and the
        table is upsert-only LWW, so both converge to the same head
        state (torn/short decodes are caught structurally and fall down
        the bootstrap chain)."""
        with self._lock:
            if self.publish_mode == "link":
                copied = self.arena.link_to(dst_path)
            else:
                # no msync first: the copy reads the same inode through
                # the page cache (always coherent with our mmap stores);
                # it is the DESTINATION that must be durable, and its
                # fsync happens below, OUTSIDE the lock — writers stall
                # only for the in-cache copy, not the disk flush
                copied = self.arena.sparse_copy_to(dst_path)
            geom = {
                "file": os.path.basename(dst_path),
                "size": HEADER_SIZE + self.arena.capacity
                * self.arena.slot_size,
                "bytes_copied": copied,
                "publish": self.publish_mode,
                "rows": self.arena.count,
                "capacity": self.arena.capacity,
                "stride": self.arena.stride,
                "key_cap": self.arena.key_cap,
                "generation": self.arena.generation,
            }
        if self.publish_mode != "link":
            # link mode skips the data fsync: flushing would msync the
            # LIVE mapping, and the journal — not the artifact — is the
            # durability source there (a short decode after a crash is
            # detected and falls back to replay)
            fd = os.open(dst_path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        return geom

    # -- metrics ----------------------------------------------------------

    def _maybe_gauges(self) -> None:
        now = time.monotonic()
        if now - self._last_gauge_ts >= 0.5:
            self._last_gauge_ts = now
            self._publish_gauges()

    def _publish_gauges(self) -> None:
        try:
            from ..obs.metrics import get_registry

            reg = get_registry()
            a = self.arena
            reg.gauge("tpums_arena_resident_bytes").set(a.resident_bytes())
            reg.gauge("tpums_arena_rows").set(a.count)
            reg.gauge("tpums_arena_index_load_factor").set(
                a.count / a.capacity if a.capacity else 0.0)
        except Exception:
            pass

    # -- checkpoint parity (MemoryStateBackend cycle) ---------------------

    def snapshot(self, checkpoint_dir: str, offset: int) -> str:
        """Same TSV-per-shard checkpoint ``ModelTable.snapshot`` writes —
        the arena is the SERVING copy; the checkpoint stays portable
        across table kinds (the O(state) fast path is
        ``serve.snapshot.publish``'s arena format, not this)."""
        with self._lock:
            rows = list(self.arena.items())
        shards: List[List[Tuple[str, str]]] = [[] for _ in
                                               range(self.n_shards)]
        for k, v in rows:
            shards[self.shard_of(k)].append((k, v))
        chk_id = f"chk-{int(time.time() * 1000)}"
        tmp = os.path.join(checkpoint_dir, f".tmp-{chk_id}")
        os.makedirs(tmp, exist_ok=True)
        for idx, shard in enumerate(shards):
            with open(os.path.join(tmp, f"shard-{idx}.tsv"), "w") as f:
                for k, v in shard:
                    f.write(f"{k}\t{v}\n")
        with open(os.path.join(tmp, "MANIFEST.json"), "w") as f:
            json.dump({"offset": offset, "n_shards": self.n_shards,
                       "ts": time.time()}, f)
        final = os.path.join(checkpoint_dir, chk_id)
        os.rename(tmp, final)
        with open(os.path.join(checkpoint_dir, "latest.tmp"), "w") as f:
            f.write(chk_id)
        os.replace(os.path.join(checkpoint_dir, "latest.tmp"),
                   os.path.join(checkpoint_dir, "latest"))
        from .table import ModelTable

        ModelTable._prune(checkpoint_dir, keep=2)
        return final

    def restore(self, checkpoint_dir: str) -> Optional[int]:
        latest_file = os.path.join(checkpoint_dir, "latest")
        if not os.path.exists(latest_file):
            return None
        with open(latest_file) as f:
            chk_id = f.read().strip()
        chk = os.path.join(checkpoint_dir, chk_id)
        with open(os.path.join(chk, "MANIFEST.json")) as f:
            manifest = json.load(f)
        keys: List[str] = []
        vals: List[str] = []
        for idx in range(int(manifest["n_shards"])):
            path = os.path.join(chk, f"shard-{idx}.tsv")
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    k, _, v = line.partition("\t")
                    keys.append(k)
                    vals.append(v)
        self.put_many_columns(keys, vals)
        return int(manifest["offset"])

    def close(self) -> None:
        with self._lock:
            if self._writer_h is not None:
                self._writer_lib.tpums_arena_writer_close(self._writer_h)
                self._writer_h = None
            self.arena.flush()
            self.arena.close()
            try:
                os.close(self._lock_fd)  # releases the flock
            except OSError:
                pass
