"""CLI entry: ALS serving job (see consumer.py; ALSKafkaConsumer parity)."""
from .consumer import als_main

if __name__ == "__main__":
    als_main()
