"""Multi-process sharded serving: N worker processes each own a hash slice
of the queryable state, with client-side key routing.

This is the scale-out dimension of the reference's serving plane:
``keyBy(0).asQueryableState`` spreads keyed state across TaskManager
subtasks and the Netty client reaches whichever subtask owns a key's shard
(``ALSKafkaConsumer.java:85-92`` + the KvState location lookup [dep]).
Here the same contract is explicit:

- every worker consumes the SAME journal topic but keeps only the keys
  with ``fnv1a(key) % num_workers == worker_index`` (the identical stable
  hash the in-process table uses for its shards, ``table.py``);
- the client routes each key to its owning worker with the same hash —
  no location service round trip, the hash IS the location;
- top-k fans out: the user's factor row is fetched from its owner, then a
  ``TOPKV`` scores every worker's catalog slice with that vector and the
  client merges the per-worker top-k by score.

Failure semantics (defined, test-pinned): queries for keys owned by a dead
worker raise ``ConnectionError`` — exactly the reference's behavior while
a subtask restarts — while every other worker keeps serving.  A restarted
worker restores its checkpoint and replays the journal from its committed
offset, after which its keys resolve again.

Worker CLI (one process per worker; ``--replicaIndex``/``--jobGroup`` mark
membership in an HA replica set — see ``serve/ha.py`` for the replicated
launcher, heartbeat supervision and client failover):

    python -m flink_ms_tpu.serve.sharded --workerIndex 0 --numWorkers 3 \
        --journalDir DIR --topic T --stateBackend fs \
        --checkpointDataUri DIR2 [--svm true] [--portFile P] \
        [--replicaIndex 0 --jobGroup G]
"""

from __future__ import annotations

import json
import os
import sys
from typing import Callable, List, Optional, Sequence, Tuple

from ..core.params import Params
from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from .client import QueryClient
from .consumer import (
    ALS_STATE,
    SVM_STATE,
    ServingJob,
    make_backend,
    parse_als_record,
    parse_svm_record,
)
from .journal import Journal
from .table import _fnv1a


def owner_of(key: str, num_workers: int) -> int:
    """The worker owning `key` — the one routing function shared by
    ingest filtering and client routing."""
    return _fnv1a(key) % num_workers


def sharded_parse(
    parse_fn: Callable[[str], Tuple[str, str]],
    worker_index: int,
    num_workers: int,
) -> Callable[[str], Optional[Tuple[str, str]]]:
    """Wrap a record parser so rows owned by other workers are skipped
    (the consume loop treats a None parse as not-mine, not an error)."""

    def parse(line: str) -> Optional[Tuple[str, str]]:
        key, value = parse_fn(line)
        if owner_of(key, num_workers) != worker_index:
            return None
        return key, value

    # advertise the wrapped parser's columnar mode plus the ownership
    # predicate so the consume loop's columnar path can split the chunk
    # with numpy and apply the SAME filter vectorized (consumer.py
    # _apply_chunk_columnar); the closure above stays the scalar fallback
    columnar_mode = getattr(parse_fn, "columnar_mode", None)
    if columnar_mode is not None:
        parse.columnar_mode = columnar_mode
        parse.shard_filter = (worker_index, num_workers)
    return parse


class ShardedQueryClient:
    """Routes queries across the worker endpoints by key hash.

    ``endpoints`` is the ordered (host, port) list — index == workerIndex.
    GET/MGET go straight to the owner; TOPK resolves the user's factors
    from their owner, then fans ``TOPKV`` to every worker and merges.
    """

    def __init__(
        self,
        endpoints: Sequence[Tuple[str, int]],
        timeout_s: float = 5.0,
        job_id: Optional[str] = None,
        seq_fanout_keys: int = 8,
        proto: Optional[str] = None,
    ):
        if not endpoints:
            raise ValueError("need at least one endpoint")
        # MGETs below this many total keys skip the thread pool and run
        # their per-owner sub-requests sequentially (see query_states)
        self.seq_fanout_keys = seq_fanout_keys
        from concurrent.futures import ThreadPoolExecutor

        # proto (serve/proto.py: tab|b2|auto; None defers to TPUMS_PROTO)
        # applies to every per-worker connection uniformly
        self._clients = [
            QueryClient(host, port, timeout_s=timeout_s, job_id=job_id,
                        proto=proto)
            for host, port in endpoints
        ]
        # persistent pool: spinning an executor up per query costs more
        # than the fan-out round trips it parallelizes.  One slot per
        # worker; per-worker QueryClients are each used by at most one
        # in-flight future at a time (futures are joined before return).
        self._pool = ThreadPoolExecutor(max_workers=len(self._clients))

    @property
    def num_workers(self) -> int:
        return len(self._clients)

    def owner(self, key: str) -> int:
        return owner_of(key, self.num_workers)

    def _count_error(self, verb: str) -> None:
        # no failover here: a raise IS client-visible — attribute it per
        # verb (same series the HA client's terminal failures land in)
        obs_metrics.get_registry().counter(
            "tpums_client_errors_total", verb=verb).inc()

    def query_state(self, name: str, key: str) -> Optional[str]:
        try:
            return self._clients[self.owner(key)].query_state(name, key)
        except (ConnectionError, OSError, TimeoutError):
            self._count_error("GET")
            raise

    def query_states(self, name: str, keys) -> list:
        """Batched lookups: one MGET per worker that owns any of the keys,
        issued CONCURRENTLY (latency ~ slowest worker, not the sum),
        results reassembled in request order."""
        keys = list(keys)
        out: List[Optional[str]] = [None] * len(keys)
        by_owner: dict = {}
        for pos, key in enumerate(keys):
            by_owner.setdefault(self.owner(key), []).append(pos)
        if len(by_owner) == 1 or len(keys) < self.seq_fanout_keys:
            # single owner, or a tiny request: pool dispatch overhead
            # exceeds the worker service time it would parallelize
            # (profiled, scripts/shard_profile.py: 2-key MGET p50 0.104 ms
            # pooled vs 0.041 ms sequential — per-worker service is
            # ~0.02 ms) — issue the sub-MGETs serially on this thread
            try:
                for w, positions in by_owner.items():
                    for p, v in zip(positions,
                                    self._clients[w].query_states(
                                        name,
                                        [keys[p] for p in positions])):
                        out[p] = v
            except (ConnectionError, OSError, TimeoutError):
                self._count_error("MGET")
                raise
            return out
        from concurrent.futures import wait as _futures_wait

        # capture the submitting request's trace context: pool threads
        # don't inherit thread-locals, and a traced fan-out must stamp
        # every shard leg with the same tid (obs/tracing.py); the
        # ``tid/sid`` composite parents each leg under the caller's
        # open span
        tid = obs_tracing.current_context()
        futures = {
            w: self._pool.submit(
                obs_tracing.call_with_trace, tid,
                self._clients[w].query_states,
                name, [keys[p] for p in positions],
            )
            for w, positions in by_owner.items()
        }
        # join EVERY future before propagating any failure: an orphaned
        # in-flight future would race the next query on its worker's
        # lock-free QueryClient socket and cross-wire replies
        _futures_wait(list(futures.values()))
        try:
            for w, positions in by_owner.items():
                for p, v in zip(positions, futures[w].result()):
                    out[p] = v
        except (ConnectionError, OSError, TimeoutError):
            self._count_error("MGET")
            raise
        return out

    def topk(self, name: str, user_id: str, k: int):
        """Fan-out top-k: returns the merged [(item, score)] best-k across
        every worker's catalog slice (scored concurrently), or None if the
        user is unknown.  Server-side, each worker's TOPKV lands in its
        cross-request microbatcher, so concurrent fan-outs from many
        clients share device dispatches per worker."""
        out = self.topk_many(name, [user_id], k)[0]
        return out

    def topk_many(self, name: str, user_ids: Sequence[str], k: int) -> list:
        """Bulk fan-out top-k for many users in one sweep: ONE MGET per
        owning worker resolves every user's factor row, then each worker
        scores ALL the query vectors through a single pipelined TOPKV
        stream (``topk_by_vector_pipelined``).  Arriving back-to-back on
        one connection, the vectors coalesce in the worker's microbatcher
        into batched device dispatches — the whole sweep costs each worker
        ~ceil(B / max_batch) catalog passes instead of B.

        Returns one merged best-k list per user id, in order; None per
        unknown user."""
        user_ids = list(user_ids)
        payloads = self.query_states(name, [f"{u}-U" for u in user_ids])
        known = [i for i, p in enumerate(payloads) if p is not None]
        out: list = [None] * len(user_ids)
        if not known:
            return out
        vecs = [payloads[i] for i in known]
        from concurrent.futures import wait as _futures_wait

        with obs_tracing.span("fanout", op="topk_many",
                              shards=self.num_workers,
                              queries=len(known), k=k):
            # capture inside the span so each shard leg parents under it
            ctx = obs_tracing.current_context()
            futs = [
                self._pool.submit(
                    obs_tracing.call_with_trace, ctx,
                    c.topk_by_vector_pipelined, name, vecs, k)
                for c in self._clients
            ]
            _futures_wait(futs)  # join all before any result() can raise
            try:
                per_worker = [f.result() for f in futs]
            except (ConnectionError, OSError, TimeoutError):
                self._count_error("TOPKV")
                raise
        for j, i in enumerate(known):
            merged: List[Tuple[str, float]] = []
            for worker_results in per_worker:
                merged.extend(worker_results[j])
            merged.sort(key=lambda it: -it[1])
            out[i] = merged[:k]
        return out

    def ping_all(self) -> List[str]:
        return [c.ping() for c in self._clients]

    def total_count(self, name: str) -> int:
        """Combined key count across every worker's slice (shards are
        disjoint by construction, so the sum is the table size)."""
        return sum(c.count(name) for c in self._clients)

    def close(self) -> None:
        # every query path joins its futures before returning, so nothing
        # is in flight here; wait=True keeps that invariant explicit
        self._pool.shutdown(wait=True)
        for c in self._clients:
            c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# worker-process lifecycle (harness/ops helpers around the CLI below)
# ---------------------------------------------------------------------------

def spawn_worker_procs(
    num_workers: int,
    journal_dir: str,
    topic: str,
    port_dir: str,
    state_backend: str = "memory",
    host: str = "127.0.0.1",
    extra_args: Sequence[str] = (),
    timeout_s: float = 120.0,
    env: Optional[dict] = None,
) -> Tuple[list, List[int]]:
    """Spawn one ``python -m flink_ms_tpu.serve.sharded`` process per shard
    and wait for every port file -> (procs, ports).

    One owner for the spawn/port-wait/cleanup dance the bench and the
    profiling harness both need: a worker that dies raises (rc included),
    a worker that hangs past ``timeout_s`` raises instead of spinning, a
    partial spawn is torn down before the exception propagates, and the
    child PYTHONPATH gets this repo PREPENDED (not clobbered — the caller
    may rely on an existing PYTHONPATH for its own deps).

    The parent environment is inherited wholesale, which is the knob
    path for the per-worker retrieval plane: ``TPUMS_TOPK_TIER`` /
    ``TPUMS_TOPK_SHARDED`` / ``TPUMS_ANN_NLIST`` / ``TPUMS_ANN_NPROBE``
    set on the launcher reach every shard worker's
    ``DeviceFactorIndex`` (each worker holds only its catalog slice, so
    its index sizes its own mesh/ANN tiers from its slice)."""
    import subprocess
    import time

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    base_env = dict(os.environ if env is None else env)
    prior = base_env.get("PYTHONPATH", "")
    base_env["PYTHONPATH"] = repo + (os.pathsep + prior if prior else "")
    procs: list = []
    try:
        port_files = []
        for widx in range(num_workers):
            pf = os.path.join(port_dir, f"shard-port-{widx}.json")
            if os.path.exists(pf):
                os.unlink(pf)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "flink_ms_tpu.serve.sharded",
                 "--workerIndex", str(widx), "--numWorkers", str(num_workers),
                 "--journalDir", journal_dir, "--topic", topic,
                 "--stateBackend", state_backend, "--host", host,
                 "--port", "0", "--portFile", pf, *extra_args],
                env=base_env, cwd=repo,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            ))
            port_files.append(pf)
        ports = []
        deadline = time.time() + timeout_s
        for p, pf in zip(procs, port_files):
            while not (os.path.exists(pf) and os.path.getsize(pf) > 0):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"shard worker died rc={p.returncode}"
                    )
                if time.time() > deadline:
                    raise RuntimeError(
                        f"shard worker port wait exceeded {timeout_s:.0f}s"
                    )
                time.sleep(0.05)
            with open(pf) as f:
                ports.append(json.load(f)["port"])
        return procs, ports
    except Exception:
        stop_worker_procs(procs)
        raise


def stop_worker_procs(procs) -> None:
    """Terminate-then-kill every worker process (idempotent, exception-safe
    — callers put this in a ``finally``)."""
    for p in procs:
        try:
            p.terminate()
        except Exception:
            pass
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:
            try:
                p.kill()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# worker CLI
# ---------------------------------------------------------------------------

def run_worker(params: Params) -> ServingJob:
    worker_index = params.get_int("workerIndex")
    num_workers = params.get_int("numWorkers")
    if worker_index is None or num_workers is None:
        raise ValueError("--workerIndex and --numWorkers are required")
    if not (0 <= worker_index < num_workers):
        raise ValueError("need 0 <= workerIndex < numWorkers")
    svm = params.get_bool("svm", False)
    state_name = SVM_STATE if svm else ALS_STATE
    base_parse = parse_svm_record if svm else parse_als_record

    journal = Journal(
        params.get_required("journalDir"), params.get_required("topic")
    )
    # HA replica-set membership (serve/ha.py): a replicated worker carries
    # its replica index and the logical shard-group id it serves, so the
    # registry can resolve the whole set and the supervisor can respawn
    # exactly the member that died
    replica_index = params.get_int("replicaIndex", None)
    job_group = params.get("jobGroup")
    replica_of = None
    if job_group or replica_index is not None:
        group = job_group or "sharded"
        replica_of = f"{group}/shard-{worker_index}"
    # elastic plane (serve/elastic.py): workers of topology generation g of
    # group G run under the generation-suffixed jobGroup "G@g<g>" (so all
    # the per-generation registry machinery above applies unchanged) and
    # additionally carry the BASE group + generation for the HEALTH hint
    topology_group = params.get("topologyGroup")
    topology_gen = params.get_int("topologyGen", None)
    # each worker checkpoints its own slice: separate subdir per index
    # (and per replica — set members must never share a checkpoint dir) so
    # restarts restore the right partition
    uri = params.get("checkpointDataUri")
    if uri:
        uri = f"{uri.rstrip('/')}/worker-{worker_index}"
        if replica_index is not None:
            uri = f"{uri}-r{replica_index}"
    backend = make_backend(params.get("stateBackend", "memory"), uri)
    default_job_id = (
        f"{job_group or 'sharded'}:s{worker_index}r{replica_index}"
        if replica_index is not None else f"worker-{worker_index}"
    )
    job = ServingJob(
        journal,
        state_name,
        sharded_parse(base_parse, worker_index, num_workers),
        backend,
        n_shards=params.get_int("shards", 8),
        checkpoint_interval_ms=params.get_int("checkPointInterval", 60_000),
        # --pollInterval: journal poll cadence in seconds.  The update
        # plane's read-your-writes latency rides on this (publish →
        # ingest → queryable), so update-heavy fleets run it much tighter
        # than the 100ms default
        poll_interval_s=params.get_float("pollInterval", 0.1),
        host=params.get("host", "0.0.0.0"),
        port=params.get_int("port", 0),
        job_id=params.get("jobId", default_job_id),
        # the C++ epoll plane per shard (requires --stateBackend rocksdb):
        # point lookups and catalog-scored TOPKV straight from each
        # worker's persistent store slice
        native_server=params.get_bool("nativeServer", False),
        ingest_mode=params.get("ingestMode"),
        replica_of=replica_of,
        replica_index=replica_index,
        topology_group=topology_group,
        generation=topology_gen,
        # snapshot-first bootstrap + background compactor knobs (defaults
        # come from TPUMS_SNAPSHOTS / TPUMS_COMPACT when flags are absent)
        snapshots=(
            params.get_bool("snapshots") if params.has("snapshots") else None
        ),
        snapshot_min_bytes=params.get_int("snapshotMinBytes"),
        compact=params.get_bool("compact") if params.has("compact") else None,
    ).start()
    print(
        f"[serve:sharded] worker {worker_index}/{num_workers}"
        + (f" replica {replica_index}" if replica_index is not None else "")
        + f" ({state_name}) on port {job.port}",
        file=sys.stderr,
    )
    # --updatePlane: co-locate the sharded online-SGD update worker with
    # this serving shard (serve/update_plane.py).  Lazy import — the plane
    # pulls in the SGD/metrics stack the plain serving path doesn't need.
    if params.get_bool("updatePlane", False):
        from . import update_plane
        job._update_worker = update_plane.attach_update_worker(
            job, params, worker_index, num_workers
        )
    port_file = params.get("portFile")
    if port_file:
        # atomic publish: launchers poll on file size, a plain write lets
        # them read a partial JSON document
        tmp_pf = port_file + ".tmp"
        with open(tmp_pf, "w") as f:
            json.dump(
                {"port": job.port, "workerIndex": worker_index,
                 "replicaIndex": replica_index, "jobId": job.job_id}, f
            )
        os.replace(tmp_pf, port_file)
    return job


def main(argv=None) -> None:
    job = run_worker(Params.from_args(sys.argv[1:] if argv is None else argv))
    job.wait()


if __name__ == "__main__":
    main()
