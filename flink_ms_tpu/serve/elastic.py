"""Elastic serving plane: topology generations, live resharding with zero
failed queries, and a metrics-driven autoscaler.

The sharded/HA planes fix the shard count at launch — ``hash%N`` ownership
is baked into every worker's ingest filter and every client's routing
table, so the reference's only answer to a traffic spike is a full
restart.  This module makes N a RUNTIME property using exactly the
primitives PRs 3-4 built: journal-replay bootstrap behind a readiness
gate, the heartbeat registry, and the fleet metrics scrape.

**Topology generations.**  A job GROUP's active shape lives in one
registry topology record ``(gen, shards, replicas)``
(``registry.publish_topology`` — atomic, CAS-guarded).  Generation g's
workers run under the generation-suffixed job group ``G@g<gen>``
(``generation_group``), so the whole per-generation stack — shard groups,
replica resolution, heartbeats, supervision, failover — is the UNCHANGED
HA machinery applied to a disposable namespace.  Topologies are
immutable: scale-out AND scale-in both mean "build generation g+1 from
the journal, cut over, drain g".

**Cutover protocol** (``ScaleController.scale_to``):

1. acquire the group's controller lease (single-writer; a second
   controller refuses, or defers until the lease frees — its choice);
2. spawn generation g+1 as a fresh ``ReplicaSupervisor`` worker set with
   ``hash%N'`` ownership; the new workers bootstrap by replaying the
   SHARED journal and register ``ready=False`` until caught up;
3. wait all-shards-ready (refreshing the lease throughout);
4. atomically publish the new topology with ``expect_gen=g`` — a CAS
   loss (``TopologyConflict``) aborts and tears g+1 down, never the
   active fleet;
5. drain: wait a grace period for clients to observe the new record,
   then stop generation g and GC its dead registry entries.

Failure model during cutover: generation g serves the WHOLE time — g+1
warming is invisible to traffic.  If g+1 dies mid-bootstrap (OOM, crash,
SIGKILL chaos), its supervisor respawns the member and replay resumes;
if bootstrap cannot complete inside the deadline the controller aborts,
tears g+1 down, and the topology record still names g — nothing
happened, no query failed.  Only after ALL of g+1 is ready does the
record flip, and the flip is atomic: a client resolves either g or g+1,
never a mix.

**Client** (``ElasticClient``): wraps ``HAShardedClient`` per generation.
It re-resolves the topology record on a refresh cadence, on a
generation-changed hint (the HEALTH verb carries ``topology_gen``, the
active generation each worker observed at heartbeat time), and on
resolution miss — a connection-class failure after the old generation
drained forces a topology re-read and ONE transparent retry against the
new generation.  Queries are idempotent reads, so the retry is always
safe; in-flight traffic rides through the swap.

**Autoscaler**: a policy loop over the obs fleet scrape
(``obs.scrape.fleet_signals``: qps, query-verb p99, ingest backlog) that
drives ``ScaleController`` with hysteresis (scale-out and scale-in
thresholds far apart) and a cooldown between operations; ``dry_run``
only logs decisions.

CLI::

    python -m flink_ms_tpu.serve.elastic --group G --journalDir D \
        --topic T --shards 2 [--replication 1] [--autoscale] [--dryRun] \
        [--minShards 1] [--maxShards 8]
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..core.params import Params
from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from . import registry
from .client import RetryPolicy
from .ha import HAShardedClient, ReplicaSupervisor, _FAILOVER_ERRORS

GEN_SEP = "@g"


def generation_group(group: str, gen: int) -> str:
    """The job group generation ``gen`` of ``group`` runs under — a
    disposable namespace the whole HA stack treats as just another
    deployment."""
    return f"{group}{GEN_SEP}{gen}"


class ControllerBusy(RuntimeError):
    """Another live controller holds the group's scaling lease."""


class ScaleError(RuntimeError):
    """The new generation could not be brought up; the active topology is
    unchanged."""


class ScaleController:
    """Owns a group's rescaling: builds each new topology generation as a
    fresh ``ReplicaSupervisor``, cuts the topology record over atomically,
    and drains the superseded generation.

    One controller instance can drive many sequential scale operations;
    concurrent operations on one GROUP are excluded by the registry
    controller lease (``defer=True`` waits for the lease instead of
    raising ``ControllerBusy``).

    ``checkpoint_uri`` (fs/rocksdb backends) is suffixed per generation
    (``.../gen-<g>``) — generations must never share checkpoint state,
    their shard counts disagree about which keys a worker owns."""

    def __init__(
        self,
        group: str,
        journal_dir: str,
        topic: str,
        port_dir: Optional[str] = None,
        state_backend: str = "memory",
        host: str = "127.0.0.1",
        replication: int = 1,
        extra_args: Sequence[str] = (),
        checkpoint_uri: Optional[str] = None,
        drain_grace_s: Optional[float] = None,
        ready_timeout_s: float = 120.0,
        defer: bool = False,
        lease_wait_s: float = 30.0,
        env: Optional[dict] = None,
        snapshots: Optional[bool] = None,
        snapshot_min_bytes: Optional[int] = None,
    ):
        # tenant-scope the group (registry.qualify_group is idempotent and
        # a no-op without TPUMS_TENANT, so single-tenant callers see the
        # exact pre-tenancy behavior)
        self.group = registry.qualify_group(group)
        self.journal_dir = journal_dir
        self.topic = topic
        self.port_dir = port_dir or tempfile.mkdtemp(prefix="tpums_elastic_")
        self.state_backend = state_backend
        self.host = host
        self.replication = replication
        self.extra_args = tuple(extra_args)
        self.snapshots = snapshots
        self.snapshot_min_bytes = snapshot_min_bytes
        self.checkpoint_uri = checkpoint_uri
        # drain grace: long enough for every client refresh cadence to
        # observe the new record before the old generation stops serving
        self.drain_grace_s = (
            2.0 * registry.heartbeat_interval_s() if drain_grace_s is None
            else drain_grace_s
        )
        self.ready_timeout_s = ready_timeout_s
        self.defer = defer
        self.lease_wait_s = lease_wait_s
        self._env = env
        self.supervisors: Dict[int, ReplicaSupervisor] = {}  # gen -> sup
        self.warming: Optional[ReplicaSupervisor] = None  # chaos target
        self.events: List[dict] = []  # cutover timeline (bench/smoke)
        self.scales = 0

    # -- introspection -----------------------------------------------------

    def current(self) -> Optional[dict]:
        return registry.resolve_topology(self.group)

    @property
    def active_supervisor(self) -> Optional[ReplicaSupervisor]:
        topo = self.current()
        if topo is None:
            return None
        return self.supervisors.get(int(topo["gen"]))

    # event-kind namespace: subclasses operating a different protocol on
    # the same machinery announce under their own prefix so the SLO layer
    # can tell a reshape from a model rollout (serve/rollout.py)
    _EVENT_PREFIX = "elastic"

    def _event(self, kind: str, **fields) -> None:
        self.events.append({"t": time.time(), "kind": kind, **fields})
        obs_tracing.events_counter(f"{self._EVENT_PREFIX}_{kind}",
                                   group=self.group, **fields)

    # -- lease -------------------------------------------------------------

    def _acquire_lease(self) -> str:
        token = registry.acquire_controller_lease(self.group)
        if token is not None:
            return token
        if not self.defer:
            raise ControllerBusy(
                f"group {self.group!r}: another controller holds the "
                "scaling lease"
            )
        deadline = time.time() + self.lease_wait_s
        while time.time() < deadline:
            time.sleep(registry.heartbeat_interval_s() / 2)
            token = registry.acquire_controller_lease(self.group)
            if token is not None:
                return token
        raise ControllerBusy(
            f"group {self.group!r}: scaling lease still held after "
            f"{self.lease_wait_s:.0f}s deferral"
        )

    # -- the cutover -------------------------------------------------------

    def _spawn_generation(self, gen: int, shards: int, replicas: int
                          ) -> ReplicaSupervisor:
        extra = list(self.extra_args)
        extra += ["--topologyGroup", self.group, "--topologyGen", str(gen)]
        # snapshot-first bootstrap knobs: a warming g+1 worker bulk-loads
        # the newest valid snapshot family published by generation g and
        # replays only the journal tail — the cutover cost stays O(state)
        # as the journal grows (serve/snapshot.py)
        if self.snapshots is not None:
            extra += ["--snapshots", "true" if self.snapshots else "false"]
        if self.snapshot_min_bytes is not None:
            extra += ["--snapshotMinBytes", str(self.snapshot_min_bytes)]
        if self.checkpoint_uri:
            extra += ["--checkpointDataUri",
                      f"{self.checkpoint_uri.rstrip('/')}/gen-{gen}"]
        return ReplicaSupervisor(
            shards, replicas, self.journal_dir, self.topic,
            os.path.join(self.port_dir, f"gen-{gen}"),
            job_group=generation_group(self.group, gen),
            state_backend=self.state_backend, host=self.host,
            extra_args=extra, env=self._env,
        )

    def _verify_generation(self, gen: int,
                           sup: ReplicaSupervisor) -> None:
        """Pre-publish verification gate, called after the all-ready
        barrier and before the CAS publish — subclass hook (the rollout
        controller row-counts and MSE-probes the warming model here,
        serve/rollout.py).  Raising aborts the cutover: the warming
        generation is torn down and the active topology stays untouched."""

    def _publish_topology(self, shards: int, replicas: int, *,
                          expect_gen: int) -> dict:
        """The CAS publish — subclass hook (the rollout controller
        attaches the generation's model binding)."""
        return registry.publish_topology(
            self.group, shards, replicas, expect_gen=expect_gen)

    def scale_to(self, shards: int, replicas: Optional[int] = None,
                 force: bool = False) -> dict:
        """Rescale the group to ``shards`` x ``replicas`` -> the published
        topology record.  Also the bootstrap path: the first call on a
        fresh group publishes generation 1.

        ``force`` builds generation g+1 even when the shape is unchanged —
        the model-rollout path, where g+1 differs by WHAT it serves, not
        by its shape (serve/rollout.py).

        Raises ``ControllerBusy`` (lease held), ``ScaleError`` (the new
        generation never became ready — it is torn down and the active
        topology is untouched), or ``registry.TopologyConflict`` (another
        controller cut over concurrently; ditto)."""
        if replicas is None:
            replicas = self.replication
        token = self._acquire_lease()
        new_sup: Optional[ReplicaSupervisor] = None
        cur_gen = 0
        try:
            topo = self.current()
            cur_gen = int(topo["gen"]) if topo else 0
            if topo and not force and int(topo["shards"]) == shards and \
                    int(topo["replicas"]) == replicas:
                return topo  # already the requested shape
            gen = cur_gen + 1
            t0 = time.time()
            self._event("scale_start", from_gen=cur_gen, to_gen=gen,
                        shards=shards, replicas=replicas)
            # expose the warming supervisor BEFORE start(): the launch
            # barrier (port-file waits) dominates bootstrap time, and the
            # chaos harness needs the whole window to target a warming
            # member — not the instant between launch and readiness
            new_sup = self._spawn_generation(gen, shards, replicas)
            self.warming = new_sup
            new_sup.start()
            # all-shards-ready barrier, in lease-refresh slices: a long
            # journal replay must not let the lease lapse and invite a
            # second controller to steal mid-bootstrap
            deadline = time.time() + self.ready_timeout_s
            ready = False
            while time.time() < deadline:
                if new_sup.wait_all_ready(timeout_s=1.0):
                    ready = True
                    break
                registry.refresh_controller_lease(self.group, token)
            if not ready:
                raise ScaleError(
                    f"generation {gen} of {self.group!r} not ready after "
                    f"{self.ready_timeout_s:.0f}s — aborting, generation "
                    f"{cur_gen} stays active"
                )
            # pre-publish verification gate (no-op here; the rollout
            # controller validates the warming MODEL before it can win)
            self._verify_generation(gen, new_sup)
            registry.refresh_controller_lease(self.group, token)
            # atomic cutover: from here on resolvers see the new shape
            record = self._publish_topology(
                shards, replicas, expect_gen=cur_gen)
            self.supervisors[gen] = new_sup
            self.warming = None
            new_sup = None  # ownership transferred; don't tear down
            self.scales += 1
            self._event("cutover", gen=gen, shards=shards,
                        replicas=replicas,
                        cutover_s=round(time.time() - t0, 3))
            self._drain(cur_gen, active_gen=gen)
            return record
        except Exception:
            if new_sup is not None:  # warming gen failed: tear it down
                self.warming = None
                try:
                    new_sup.stop()
                except Exception:
                    pass
                self._event("scale_abort", to_gen=cur_gen + 1)
            raise
        finally:
            registry.release_controller_lease(self.group, token)

    def _drain(self, gen: int, active_gen: int) -> None:
        """Retire a superseded generation: grace for clients to swap, stop
        its supervisor (if this controller owns it), GC its dead entries."""
        if gen <= 0:
            return
        time.sleep(self.drain_grace_s)
        old = self.supervisors.pop(gen, None)
        if old is not None:
            old.stop()
        reaped = registry.gc_generation_entries(self.group, active_gen)
        self._event("drained", gen=gen, reaped=reaped)

    # -- lifecycle ---------------------------------------------------------

    def client(self, **kw) -> "ElasticClient":
        kw.setdefault("group", self.group)
        return ElasticClient(**kw)

    def stop(self, drop_topology: bool = False) -> None:
        """Stop every generation this controller owns (teardown, not a
        cutover).  ``drop_topology`` also removes the group's record."""
        self.warming = None
        for gen in sorted(self.supervisors):
            try:
                self.supervisors.pop(gen).stop()
            except Exception:
                pass
        if drop_topology:
            registry.drop_topology(self.group)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class ElasticClient:
    """Topology-following client: resolves the group's active generation,
    serves queries through a per-generation ``HAShardedClient``, and swaps
    generations underneath in-flight traffic.

    Re-resolution triggers (any one suffices):

    - cadence: every ``refresh_s`` (default: the heartbeat interval) the
      topology record is re-read — one small local file read;
    - hint: callers may feed ``note_topology_gen()`` with the
      ``topology_gen`` field a HEALTH reply carried;
    - miss: a connection-class failure that exhausted the inner client's
      failover budget forces a topology re-read, and if the generation
      moved the call transparently retries ONCE on the new generation
      (idempotent reads make this always safe).

    Not thread-safe (same contract as ``HAShardedClient``)."""

    def __init__(
        self,
        group: str,
        timeout_s: float = 5.0,
        retry: Optional[RetryPolicy] = None,
        refresh_s: Optional[float] = None,
        resolve_timeout_s: float = 30.0,
        **client_kw,
    ):
        # same tenant scoping as the controller: with TPUMS_TENANT set,
        # client and controller resolve the same qualified record
        self.group = registry.qualify_group(group)
        self.timeout_s = timeout_s
        self.retry = retry
        self.refresh_s = (
            registry.heartbeat_interval_s() if refresh_s is None
            else refresh_s
        )
        self._client_kw = client_kw
        self.generation = 0
        self.num_workers = 0
        self.generation_swaps = 0
        self._inner: Optional[HAShardedClient] = None
        self._last_refresh = 0.0
        self._hinted_gen = 0
        deadline = time.time() + resolve_timeout_s
        while True:
            if self._maybe_swap(force=True):
                break
            if time.time() > deadline:
                raise ConnectionError(
                    f"no topology record for group {group!r} after "
                    f"{resolve_timeout_s:.0f}s"
                )
            time.sleep(0.05)

    # -- topology tracking -------------------------------------------------

    def note_topology_gen(self, gen: Optional[int]) -> None:
        """Feed a generation-changed hint (the ``topology_gen`` field of a
        HEALTH reply); a gen ahead of ours forces re-resolution on the
        next call."""
        if gen is not None and int(gen) > self.generation:
            self._hinted_gen = int(gen)

    def _maybe_swap(self, force: bool = False) -> bool:
        """Re-read the topology record when due -> True if a client for
        the active generation is installed."""
        now = time.monotonic()
        if not force and self._inner is not None and \
                self._hinted_gen <= self.generation and \
                now - self._last_refresh < self.refresh_s:
            return True
        self._last_refresh = now
        topo = self._resolve_topology_retrying()
        if topo is None:
            return self._inner is not None
        gen = int(topo["gen"])
        if gen == self.generation and self._inner is not None:
            return True
        old = self._inner
        self._inner = HAShardedClient(
            int(topo["shards"]),
            job_group=generation_group(self.group, gen),
            timeout_s=self.timeout_s, retry=self.retry,
            **self._client_kw,
        )
        self.generation = gen
        self.num_workers = int(topo["shards"])
        self._hinted_gen = 0
        if old is not None:
            self.generation_swaps += 1
            obs_tracing.event("generation_swap", group=self.group, gen=gen,
                              shards=self.num_workers)
            try:
                old.close()
            except Exception:
                pass
        return True

    def _resolve_topology_retrying(self):
        """Topology read with the read ERROR distinguished from the record
        being GONE.  A transient registry failure (unreadable dir, torn
        write beyond the registry's own one-re-read guard) used to look
        identical to "no record" and was silently swallowed; now it earns
        a short bounded backoff and a counter, and on persistent failure
        the caller keeps serving the last known generation."""
        delay = 0.01
        for attempt in range(3):
            try:
                return registry.resolve_topology(self.group, strict=True)
            except (OSError, ValueError):
                obs_metrics.get_registry().counter(
                    "tpums_client_topology_refresh_errors_total",
                    group=self.group).inc()
                if attempt < 2:
                    time.sleep(delay)
                    delay *= 4
        return None

    def _call(self, op: str, *args):
        self._maybe_swap()
        try:
            return getattr(self._inner, op)(*args)
        except _FAILOVER_ERRORS:
            # resolution miss: the set may be a drained generation — force
            # a topology re-read; a moved generation earns ONE retry
            was = self.generation
            self._maybe_swap(force=True)
            if self.generation == was:
                raise
            # absorbed by the swap: count it per verb so the SLO layer can
            # attribute cutover-window retries separately from failovers
            obs_metrics.get_registry().counter(
                "tpums_client_gen_retries_total",
                verb=HAShardedClient._OP_VERB.get(op, op.upper())).inc()
            return getattr(self._inner, op)(*args)

    # -- query surface (HAShardedClient-compatible) ------------------------

    def query_state(self, name: str, key: str):
        return self._call("query_state", name, key)

    def query_states(self, name: str, keys) -> list:
        return self._call("query_states", name, list(keys))

    def topk(self, name: str, user_id: str, k: int):
        return self._call("topk", name, user_id, k)

    def topk_many(self, name: str, user_ids, k: int) -> list:
        return self._call("topk_many", name, list(user_ids), k)

    def total_count(self, name: str) -> int:
        return self._call("total_count", name)

    def shard_health(self, name: str, shard: int) -> dict:
        report = self._call("shard_health", name, shard)
        self.note_topology_gen(report.get("topology_gen"))
        return report

    def ping_all(self) -> List[str]:
        return self._call("ping_all")

    def close(self) -> None:
        if self._inner is not None:
            self._inner.close()
            self._inner = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

@dataclass
class AutoscalerPolicy:
    """Hysteresis thresholds for the scaling decision.  ``decide`` is pure
    (no I/O, no clock reads beyond its arguments) so the policy is unit-
    testable without a fleet.

    Scale-OUT when any pressure signal crosses its high mark; scale-IN
    only when EVERY signal sits below the low marks — the wide gap between
    ``qps_high_per_shard`` and ``qps_low_per_shard`` is the hysteresis
    band that keeps a load level near one threshold from flapping the
    fleet, and ``cooldown_s`` spaces operations out so a fresh
    generation's warmup never feeds the next decision."""

    qps_high_per_shard: float = 500.0
    qps_low_per_shard: float = 100.0
    p99_high_s: float = 0.050
    backlog_high_bytes: int = 8 << 20
    min_shards: int = 1
    max_shards: int = 8
    cooldown_s: float = 30.0

    def decide(self, signals: dict, current_shards: int, now: float,
               last_scale_t: float) -> dict:
        """-> {"target": shards|None, "reason": str}."""
        if now - last_scale_t < self.cooldown_s:
            return {"target": None, "reason": "cooldown"}
        qps = signals.get("qps") or 0.0
        p99 = signals.get("p99_s")
        backlog = signals.get("backlog_bytes") or 0
        per_shard = qps / max(current_shards, 1)
        pressure = []
        if per_shard > self.qps_high_per_shard:
            pressure.append(f"qps/shard {per_shard:.0f} > "
                            f"{self.qps_high_per_shard:.0f}")
        if p99 is not None and p99 > self.p99_high_s:
            pressure.append(f"p99 {p99 * 1e3:.1f}ms > "
                            f"{self.p99_high_s * 1e3:.1f}ms")
        if backlog > self.backlog_high_bytes:
            pressure.append(f"backlog {backlog} > {self.backlog_high_bytes}")
        if pressure:
            target = min(current_shards * 2, self.max_shards)
            if target > current_shards:
                return {"target": target, "reason": "; ".join(pressure)}
            return {"target": None, "reason": "at max_shards: "
                    + "; ".join(pressure)}
        calm = (
            per_shard < self.qps_low_per_shard
            and (p99 is None or p99 < self.p99_high_s / 2)
            and backlog < self.backlog_high_bytes // 4
        )
        if calm:
            target = max(current_shards // 2, self.min_shards)
            if target < current_shards:
                return {
                    "target": target,
                    "reason": f"qps/shard {per_shard:.0f} < "
                              f"{self.qps_low_per_shard:.0f}",
                }
        return {"target": None, "reason": "steady"}


class Autoscaler:
    """Policy loop: scrape the fleet on a cadence, turn the window into
    signals (``obs.scrape.fleet_signals``), ask the policy, drive the
    controller.  ``dry_run`` logs the decision it WOULD take and touches
    nothing — the mode an operator trials a policy in before handing it
    the fleet."""

    def __init__(
        self,
        controller: ScaleController,
        policy: Optional[AutoscalerPolicy] = None,
        interval_s: float = 5.0,
        dry_run: bool = False,
    ):
        self.controller = controller
        self.policy = policy or AutoscalerPolicy()
        self.interval_s = interval_s
        self.dry_run = dry_run
        self.decisions: List[dict] = []
        self.last_scale_t = 0.0
        self._prev_fleet: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> dict:
        """One observe -> decide -> (maybe) act cycle -> the decision."""
        from ..obs.scrape import fleet_signals, scrape_fleet

        fleet = scrape_fleet()["fleet"]
        if self._prev_fleet is None:
            self._prev_fleet = fleet
            return {"target": None, "reason": "first scrape (no window)"}
        signals = fleet_signals(self._prev_fleet, fleet)
        self._prev_fleet = fleet
        topo = self.controller.current()
        shards = int(topo["shards"]) if topo else 0
        decision = self.policy.decide(
            signals, shards, time.time(), self.last_scale_t)
        decision.update(signals=signals, current_shards=shards,
                        dry_run=self.dry_run, t=time.time())
        self.decisions.append(decision)
        target = decision["target"]
        if target is not None and shards:
            obs_tracing.events_counter(
                "autoscale_decision", group=self.controller.group,
                target=target, reason=decision["reason"],
                dry_run=self.dry_run)
            if self.dry_run:
                print(f"[elastic:dry-run] would scale "
                      f"{self.controller.group} {shards} -> {target} "
                      f"({decision['reason']})", file=sys.stderr)
            else:
                try:
                    self.controller.scale_to(target)
                    self.last_scale_t = time.time()
                except (ControllerBusy, registry.TopologyConflict,
                        ScaleError) as e:
                    decision["error"] = str(e)
        return decision

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                pass  # the loop must outlive transient scrape errors

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def run_elastic(params: Params) -> ScaleController:
    # worker-arg passthrough (mirrors ha.run_supervisor): every generation's
    # ReplicaSupervisor spawns workers with these, so a --nativeServer true
    # deployment rescales between NATIVE fleets — the warming generation's
    # C++ servers answer the readiness gate's HEALTH probes themselves
    extra: List[str] = []
    for passthrough in ("svm", "checkPointInterval", "nativeServer",
                        "ingestMode", "snapshots", "snapshotMinBytes",
                        "compact", "updatePlane", "updatePartitions",
                        "updateBatch", "pollInterval"):
        if params.has(passthrough):
            extra += [f"--{passthrough}", params.get(passthrough)]
    ctl = ScaleController(
        params.get("group", "elastic"),
        params.get_required("journalDir"), params.get_required("topic"),
        port_dir=params.get("portDir"),
        state_backend=params.get("stateBackend", "memory"),
        host=params.get("host", "127.0.0.1"),
        replication=params.get_int("replication", 1),
        extra_args=extra,
        checkpoint_uri=params.get("checkpointDataUri"),
    )
    record = ctl.scale_to(params.get_int("shards", 2))
    print(
        f"[serve:elastic] group {ctl.group} generation {record['gen']}: "
        f"{record['shards']} shard(s) x {record['replicas']} replica(s)",
        file=sys.stderr,
    )
    return ctl


def main(argv=None) -> None:
    import signal

    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    ctl = run_elastic(params)
    scaler: Optional[Autoscaler] = None
    if params.get_bool("autoscale", False):
        scaler = Autoscaler(
            ctl,
            AutoscalerPolicy(
                min_shards=params.get_int("minShards", 1),
                max_shards=params.get_int("maxShards", 8),
                cooldown_s=float(params.get("cooldownS", "30")),
            ),
            interval_s=float(params.get("scrapeIntervalS", "5")),
            dry_run=params.get_bool("dryRun", False),
        ).start()
        print(f"[serve:elastic] autoscaler on "
              f"({'dry-run' if scaler.dry_run else 'live'})",
              file=sys.stderr)
    stop = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:
        pass
    try:
        while not stop.is_set():
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    if scaler is not None:
        scaler.stop()
    ctl.stop()


if __name__ == "__main__":
    main()
