"""Replayable ingest journal — the TPU-native stand-in for the reference's
Kafka 0.10 + ZooKeeper model bus (SURVEY.md §2.5).

A topic is an append-only log file under a journal directory.  Producers
append model rows (``ALSKafkaProducer.java:29-37`` writes with
``flushOnCheckpoint`` = at-least-once); consumers poll from a byte offset
and commit that offset in their checkpoints, so replay after failure
re-delivers rows — duplicates are tolerated by design because the serving
table is last-writer-wins, exactly like the reference's ``ValueState``
(``ALSKafkaConsumer.java:85-92``).

The log format is plain text lines, so journals are interoperable with the
reference's model files and greppable during ops.
"""

from __future__ import annotations

import os
import threading
from typing import Iterable, List, Tuple


class Journal:
    """One topic inside a journal directory."""

    def __init__(self, journal_dir: str, topic: str):
        if not topic or "/" in topic or topic.startswith("."):
            raise ValueError(f"invalid topic name: {topic!r}")
        self.dir = journal_dir
        self.topic = topic
        os.makedirs(journal_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, f"{topic}.log")
        self._lock = threading.Lock()

    # -- producer side -----------------------------------------------------

    def append(self, lines: Iterable[str], flush: bool = True) -> int:
        """Append lines; returns the end offset.  ``flush`` fsyncs — the
        analog of the producer's flushOnCheckpoint (at-least-once)."""
        with self._lock:
            with open(self.path, "a") as f:
                for line in lines:
                    if "\n" in line:
                        raise ValueError("journal records are single lines")
                    f.write(line)
                    f.write("\n")
                f.flush()
                if flush:
                    os.fsync(f.fileno())
                return f.tell()

    def sync(self) -> None:
        """fsync the topic file without writing — the checkpoint-boundary
        flush for producers appending with ``flush=False``."""
        with self._lock:
            try:
                with open(self.path, "a") as f:
                    os.fsync(f.fileno())
            except FileNotFoundError:
                pass

    # -- consumer side -----------------------------------------------------

    def end_offset(self) -> int:
        try:
            return os.path.getsize(self.path)
        except FileNotFoundError:
            return 0

    def read_bytes_from(
        self, offset: int, max_bytes: int = 1 << 24
    ) -> Tuple[bytes, int]:
        """Poll the raw complete-lines byte chunk after ``offset`` —
        (chunk ending at its last newline, next_offset).  The zero-decode
        variant of ``read_from`` for native bulk ingest."""
        if not os.path.exists(self.path):
            return b"", offset
        with open(self.path, "rb") as f:
            f.seek(offset)
            chunk = f.read(max_bytes)
        if not chunk:
            return b"", offset
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            return b"", offset
        complete = chunk[: last_nl + 1]
        return complete, offset + len(complete)

    def read_from(self, offset: int, max_bytes: int = 1 << 24) -> Tuple[List[str], int]:
        """Poll records after `offset`; returns (lines, next_offset).

        Only complete lines are returned; a torn tail (producer mid-append)
        stays unconsumed until its newline lands.
        """
        complete, next_offset = self.read_bytes_from(offset, max_bytes)
        if not complete:
            return [], offset
        return complete.decode("utf-8").splitlines(), next_offset
