"""Replayable ingest journal — the TPU-native stand-in for the reference's
Kafka 0.10 + ZooKeeper model bus (SURVEY.md §2.5).

A topic is an append-only log under a journal directory.  Producers append
model rows (``ALSKafkaProducer.java:29-37`` writes with
``flushOnCheckpoint`` = at-least-once); consumers poll from a byte offset
and commit that offset in their checkpoints, so replay after failure
re-delivers rows — duplicates are tolerated by design because the serving
table is last-writer-wins, exactly like the reference's ``ValueState``
(``ALSKafkaConsumer.java:85-92``).

Topics are SEGMENTED like Kafka's log: the active segment receives
appends; when ``segment_bytes`` is configured, a full segment is sealed
and a new one starts at the current end offset, and ``retain_segments``
bounds disk by deleting the oldest sealed segments.  Offsets are global
byte positions (segment base + position), contiguous across rotation, so
consumer checkpoints are unaffected.

Compaction (Kafka's log-compacted-topic semantics, the property the
reference's model transport rides): sealed segments may be FOLDED
last-writer-wins per key into a single compacted prefix segment
(``<topic>.clog.<base>.<logical_end>``).  The compacted segment keeps the
global-byte-offset contract by carrying both its base offset and the
logical end offset of the history it replaces: a reader AT the base gets
the folded rows and then jumps to ``logical_end``, where the untouched
tail segments continue at their original offsets — live tailers past the
fold never notice.  When a compacted prefix exists, ``retain_segments``
stops blind-deleting: retention becomes "compacted prefix + tail" and the
compactor bounds disk instead (see ``serve/compact.py``).

A reader whose offset points at history that no longer exists byte-for-
byte gets a typed ``OffsetTruncatedError`` — never a silent skip.  Two
flavors: an offset below the earliest retained base names rows that are
GONE (``lossless=False``; resuming at ``resume_offset`` loses data and
must be an explicit, counted decision), while an offset strictly inside a
compacted prefix names rows that were folded (``lossless=True``; resuming
at ``resume_offset`` — the prefix base — re-reads a last-writer-wins
superset, so state converges with zero loss).  Callers opt back into the
old Kafka ``auto.offset.reset=earliest`` behavior with
``on_truncated="reset"``, which counts the skipped bytes in
``expired_bytes_skipped``.

The log format is plain text lines, so journals are interoperable with the
reference's model files and greppable during ops.  Segment files are
``<topic>.log`` (base offset 0), ``<topic>.log.<base>``, and
``<topic>.clog.<base>.<logical_end>`` for the compacted prefix.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Iterable, List, NamedTuple, Optional, Tuple


class OffsetTruncatedError(RuntimeError):
    """A reader's offset points at journal history that no longer exists
    byte-for-byte.

    Attributes:
        offset:        the offset the reader asked for
        resume_offset: the earliest offset a replay may resume from
        lossless:      True when resuming at ``resume_offset`` re-delivers
                       a last-writer-wins superset of the missing range (a
                       compacted prefix); False when the rows are gone
                       (retention deleted them) and resuming skips data
    """

    def __init__(self, offset: int, resume_offset: int, lossless: bool,
                 reason: str):
        super().__init__(
            f"offset {offset} truncated ({reason}); resume at "
            f"{resume_offset} ({'lossless' if lossless else 'LOSSY'})"
        )
        self.offset = offset
        self.resume_offset = resume_offset
        self.lossless = lossless
        self.reason = reason


class _Seg(NamedTuple):
    base: int
    path: str
    # logical end offset for a COMPACTED segment (the history it replaced
    # ran [base, logical_end)); None for a plain segment
    logical_end: Optional[int]


class Journal:
    """One topic inside a journal directory."""

    def __init__(
        self,
        journal_dir: str,
        topic: str,
        segment_bytes: Optional[int] = None,
        retain_segments: Optional[int] = None,
    ):
        if not topic or "/" in topic or topic.startswith("."):
            raise ValueError(f"invalid topic name: {topic!r}")
        if segment_bytes is not None and segment_bytes < 1:
            raise ValueError("segment_bytes must be >= 1")
        if retain_segments is not None and retain_segments < 1:
            raise ValueError("retain_segments must be >= 1")
        self.dir = journal_dir
        self.topic = topic
        self.segment_bytes = segment_bytes
        self.retain_segments = retain_segments
        os.makedirs(journal_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, f"{topic}.log")  # base-0 segment
        self._lock = threading.Lock()
        self.expired_bytes_skipped = 0  # consumer-side observability
        self.torn_bytes_skipped = 0     # newline-less tails of sealed segments
        self.compacted_rereads = 0      # reset-mode restarts into a fold
        self._seg_cache: Optional[List[_Seg]] = None
        # producer-side (dir_mtime_ns, base, path) — see _active_segment
        self._active_cache: Optional[Tuple[int, int, str]] = None

    # -- segment layout ------------------------------------------------------

    def _scan(self) -> List[_Seg]:
        """All raw segment files on disk, sorted by (base, plain-first)."""
        plain = f"{self.topic}.log"
        clog = f"{self.topic}.clog."
        out: List[_Seg] = []
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return []
        for name in names:
            if name == plain:
                out.append(_Seg(0, os.path.join(self.dir, name), None))
            elif name.startswith(plain + "."):
                suffix = name[len(plain) + 1:]
                try:
                    out.append(
                        _Seg(int(suffix), os.path.join(self.dir, name), None)
                    )
                except ValueError:
                    continue  # unrelated file
            elif name.startswith(clog):
                parts = name[len(clog):].split(".")
                if len(parts) != 2:
                    continue  # in-flight tmp file or unrelated
                try:
                    base, lend = int(parts[0]), int(parts[1])
                except ValueError:
                    continue
                if lend > base:
                    out.append(
                        _Seg(base, os.path.join(self.dir, name), lend)
                    )
        out.sort(key=lambda s: (s.base, s.logical_end is None,
                                -(s.logical_end or 0)))
        return out

    @staticmethod
    def _shadow(raw: List[_Seg]) -> List[_Seg]:
        """Resolve the reader view: a compacted segment shadows every
        segment whose base falls inside its [base, logical_end) range —
        the plain originals it folded (kept briefly during the atomic
        swap, or left by a crash mid-cleanup) and any older, narrower
        fold."""
        folds = [s for s in raw if s.logical_end is not None]
        view: List[_Seg] = []
        for s in raw:
            shadowed = any(
                f is not s
                and f.base <= s.base < f.logical_end
                and (s.logical_end is None or s.logical_end <= f.logical_end)
                for f in folds
            )
            if not shadowed:
                view.append(s)
        return view

    def _segments(self) -> List[Tuple[int, str]]:
        """Sorted [(base_offset, path)] of the reader-visible segments
        (compacted prefix included, shadowed leftovers excluded)."""
        return [(s.base, s.path) for s in self._shadow(self._scan())]

    def _view(self) -> List[_Seg]:
        return self._shadow(self._scan())

    def _view_cached(self, refresh: bool = False) -> List[_Seg]:
        """Consumer-side segment view; one os.listdir only when the cache
        is cold, explicitly refreshed, or the topic has no known segments
        (a poll on the hot path must not list the whole journal dir)."""
        if refresh or not self._seg_cache:
            self._seg_cache = self._view()
        return self._seg_cache

    def _active_segment(self) -> Tuple[int, str]:
        """The append target: the highest-base plain segment, or a fresh
        plain segment at ``logical_end`` when the whole log is one fold.

        One os.stat of the directory validates a cached answer: a roll,
        fold or truncation by ANY process creates or removes a directory
        entry and therefore bumps the dir mtime, while plain appends do
        not — so a matching mtime proves the cached layout is current
        (the per-append os.listdir was a measured hot spot once the
        update plane put 30+ producer topics in one journal dir).  A
        fresh mtime is never cached: filesystem timestamps tick coarsely,
        and a concurrent roll inside the same tick would otherwise stay
        invisible forever."""
        try:
            dir_mtime = os.stat(self.dir).st_mtime_ns
        except OSError:
            dir_mtime = None
        cached = self._active_cache
        if cached is not None and dir_mtime is not None \
                and cached[0] == dir_mtime:
            return cached[1], cached[2]
        self._active_cache = None
        base, path = self._active_segment_scan()
        if dir_mtime is not None and \
                time.time_ns() - dir_mtime > 50_000_000:
            self._active_cache = (dir_mtime, base, path)
        return base, path

    def _active_segment_scan(self) -> Tuple[int, str]:
        view = self._view()
        if not view:
            return 0, self.path
        last = view[-1]
        if last.logical_end is not None:
            # fully-compacted log: appends restart a plain segment exactly
            # at the fold's logical end, keeping offsets contiguous
            return last.logical_end, os.path.join(
                self.dir, f"{self.topic}.log.{last.logical_end}"
            )
        return last.base, last.path

    # -- producer side -------------------------------------------------------

    def append(self, lines: Iterable[str], flush: bool = True) -> int:
        """Append lines; returns the end offset.  ``flush`` fsyncs — the
        analog of the producer's flushOnCheckpoint (at-least-once)."""
        with self._lock:
            base, path = self._active_segment()
            try:
                size = os.path.getsize(path)
            except FileNotFoundError:
                size = 0
            if (
                self.segment_bytes is not None
                and size >= self.segment_bytes
            ):
                # Seal the segment.  Two invariants are established here:
                # (1) durability — sync()/flush=True only reach the ACTIVE
                # segment, so the sealed one must be fsynced now or a crash
                # could drop its page-cache tail while later segments
                # survive; (2) newline termination — a torn tail from a
                # crashed producer can never complete once sealed, so it
                # is terminated into a malformed row the consumer's
                # skip-and-count policy handles, instead of wedging every
                # consumer at a line that never ends.
                with open(path, "rb+") as sf:
                    sf.seek(0, os.SEEK_END)
                    if sf.tell() > 0:
                        sf.seek(-1, os.SEEK_END)
                        if sf.read(1) != b"\n":
                            sf.write(b"\n")
                    sf.flush()
                    os.fsync(sf.fileno())
                    size = sf.tell()
                base = base + size
                path = os.path.join(
                    self.dir, f"{self.topic}.log.{base}"
                )
                self._apply_retention_locked()
            with open(path, "a") as f:
                for line in lines:
                    if "\n" in line:
                        raise ValueError("journal records are single lines")
                    f.write(line)
                    f.write("\n")
                f.flush()
                if flush:
                    os.fsync(f.fileno())
                return base + f.tell()

    def _apply_retention_locked(self) -> None:
        raw = self._scan()
        view = self._shadow(raw)
        # leftovers a fold superseded are garbage regardless of policy:
        # delete them (also finishes the cleanup a compactor crash left)
        visible = {s.path for s in view}
        for s in raw:
            if s.path not in visible:
                try:
                    os.remove(s.path)
                except OSError:
                    pass
        if self.retain_segments is None:
            return
        if any(s.logical_end is not None for s in view):
            # compacted prefix present: retention is "compacted prefix +
            # tail".  Blind deletion of sealed tail segments would strand
            # readers AND race the compactor that is about to fold them —
            # the compactor bounds disk by folding, not retention.
            return
        # +1: the about-to-be-created active segment counts toward the bound
        excess = len(view) + 1 - self.retain_segments
        for s in view[:max(excess, 0)]:
            try:
                os.remove(s.path)
            except OSError:
                pass

    def sync(self) -> None:
        """fsync the active segment without writing — the checkpoint-boundary
        flush for producers appending with ``flush=False``."""
        with self._lock:
            _, path = self._active_segment()
            try:
                with open(path, "a") as f:
                    os.fsync(f.fileno())
            except FileNotFoundError:
                pass

    # -- compaction (serve/compact.py drives this) ---------------------------

    def compact_prefix(
        self,
        fold_fn: Callable[[bytes], bytes],
        min_segments: int = 2,
    ) -> Optional[dict]:
        """Fold every SEALED segment (all but the active one) into a single
        compacted prefix segment, last-writer-wins per key.

        ``fold_fn`` receives the concatenated bytes of the sealed prefix
        (complete, newline-terminated rows in journal order) and returns
        the folded bytes — key semantics live in ``serve/compact.py`` so
        the journal stays format-agnostic.  The swap is atomic: the fold
        is written to a tmp file, fsynced, renamed to
        ``<topic>.clog.<base>.<logical_end>``, and only then are the
        folded originals deleted — a reader either sees the old segments
        or the complete fold, never a torn mix, and a SIGKILL at any point
        leaves a valid segment set (the tmp file is invisible to
        ``_scan`` and the shadow rule hides not-yet-deleted originals).

        Returns a stats dict, or None when there is nothing to fold (fewer
        than ``min_segments`` sealed segments, or no new sealed rows since
        the previous fold) or the prefix raced retention/another fold.
        """
        view = self._view()
        if len(view) < 2:
            return None  # nothing sealed: never fold the active segment
        prefix = view[:-1]
        if not any(s.logical_end is None for s in prefix):
            return None  # fold already covers every sealed row
        if len(prefix) < max(min_segments, 1):
            return None
        contents: List[bytes] = []
        rotted = False
        for s in prefix:
            try:
                with open(s.path, "rb") as f:
                    contents.append(f.read())
            except (FileNotFoundError, OSError):
                rotted = True  # raced retention/another compactor: retry later
                break
        if rotted:
            return None
        data = b"".join(contents)
        folded = fold_fn(data)
        if folded and not folded.endswith(b"\n"):
            folded += b"\n"
        base = prefix[0].base
        logical_end = view[-1].base  # first offset NOT folded (the tail)
        if logical_end <= base:
            return None
        final = os.path.join(
            self.dir, f"{self.topic}.clog.{base}.{logical_end}"
        )
        tmp = f"{final}.tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(folded)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, final)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        with self._lock:
            for s in prefix:
                if s.path != final:
                    try:
                        os.remove(s.path)
                    except OSError:
                        pass
            self._seg_cache = None
        return {
            "segments_folded": len(prefix),
            "base": base,
            "logical_end": logical_end,
            "bytes_in": len(data),
            "bytes_out": len(folded),
            "bytes_reclaimed": len(data) - len(folded),
        }

    # -- consumer side -------------------------------------------------------

    def start_offset(self) -> int:
        """Earliest retained offset (0 unless retention expired segments)."""
        view = self._view()
        return view[0].base if view else 0

    def end_offset(self) -> int:
        base, path = self._active_segment()
        try:
            return base + os.path.getsize(path)
        except FileNotFoundError:
            return base

    def aligned_end_offset(self) -> int:
        """End offset clamped to the last record boundary: a producer
        mid-append leaves a newline-less tail that ``end_offset`` counts
        but no reader may start inside (consumers seeded at ``latest``
        use this so their first poll is line-aligned)."""
        base, path = self._active_segment()
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                pos = size
                while pos > 0:
                    step = min(1 << 16, pos)
                    f.seek(pos - step)
                    chunk = f.read(step)
                    nl = chunk.rfind(b"\n")
                    if nl >= 0:
                        return base + pos - step + nl + 1
                    pos -= step
        except FileNotFoundError:
            pass
        return base

    def tail_line(self) -> Optional[str]:
        """The last COMPLETE record of the topic, or None when empty.

        The O(tail) watermark read of the update plane
        (``serve/update_plane.py``): recovery and progress polling need
        only the newest committed record, not a full replay.  Same
        reverse-scan idiom as ``aligned_end_offset``; a newline-less torn
        tail (producer mid-append / SIGKILLed) is skipped — by
        construction it was never committed."""
        for seg in reversed(self._view()):
            try:
                with open(seg.path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    pos = f.tell()
                    if pos == 0:
                        continue
                    buf = b""
                    while pos > 0:
                        step = min(1 << 16, pos)
                        f.seek(pos - step)
                        buf = f.read(step) + buf
                        pos -= step
                        # need the terminator of the last complete line AND
                        # the newline (or BOF) that precedes it
                        if buf.count(b"\n") >= 2:
                            break
            except (FileNotFoundError, OSError):
                continue
            last_nl = buf.rfind(b"\n")
            if last_nl < 0:
                continue  # only a torn tail in this segment: look earlier
            start = buf.rfind(b"\n", 0, last_nl) + 1
            return buf[start:last_nl].decode("utf-8")
        return None

    def read_bytes_from(
        self, offset: int, max_bytes: int = 1 << 24,
        on_truncated: str = "raise",
    ) -> Tuple[bytes, int]:
        """Poll the raw complete-lines byte chunk after ``offset`` —
        (chunk ending at its last newline, next_offset).  The zero-decode
        variant of ``read_from`` for native bulk ingest.

        An offset pointing at history that no longer exists byte-for-byte
        (expired by retention, or folded into a compacted prefix) raises
        ``OffsetTruncatedError`` so the caller can bootstrap from a
        snapshot instead of silently skipping rows.
        ``on_truncated="reset"`` opts back into the old
        ``auto.offset.reset=earliest`` behavior: resume at the earliest
        replayable offset, counting lost bytes in
        ``expired_bytes_skipped`` (a compacted-prefix restart is lossless
        and counts in ``compacted_rereads`` instead).

        A read that lands exactly on a compacted prefix base returns the
        WHOLE folded prefix in one chunk, ``max_bytes`` notwithstanding:
        intermediate positions inside a fold are not valid offsets (the
        fold is O(state), the same bound as a snapshot bulk-load).
        """
        if on_truncated not in ("raise", "reset"):
            raise ValueError("on_truncated must be raise|reset")
        try:
            out = self._try_read(offset, max_bytes, refresh=False)
            if out is not None and (out[0] or out[1] != offset):
                return out
            # nothing advanced with the cached layout: rescan once — a new
            # segment may have been rolled, retention may have moved the
            # earliest base, or a fold may have replaced the prefix — then
            # report whatever the fresh view yields
            out = self._try_read(offset, max_bytes, refresh=True)
            return out if out is not None else (b"", offset)
        except OffsetTruncatedError as e:
            if on_truncated != "reset":
                raise
            if e.lossless:
                self.compacted_rereads += 1
            else:
                self.expired_bytes_skipped += e.resume_offset - offset
            return self.read_bytes_from(
                e.resume_offset, max_bytes, on_truncated="reset"
            )

    def _try_read(
        self, offset: int, max_bytes: int, refresh: bool
    ) -> Optional[Tuple[bytes, int]]:
        segs = self._view_cached(refresh)
        if not segs:
            return None
        if offset < segs[0].base:
            if not refresh:
                return None  # stale cache must not fabricate a truncation
            raise OffsetTruncatedError(
                offset, segs[0].base, lossless=False,
                reason="below earliest retained segment",
            )
        seg = segs[0]
        for s in reversed(segs):
            if offset >= s.base:
                seg = s
                break
        if seg.logical_end is not None:
            return self._read_compacted(seg, offset, max_bytes, refresh)
        base, path = seg.base, seg.path
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(offset - base)
                chunk = f.read(max_bytes)
        except FileNotFoundError:  # expired/folded between scan and read
            return None
        sealed_end = next(
            (s.base for s in segs if s.base > base), None
        )  # this segment is sealed iff a later one exists
        if not chunk:
            if sealed_end is not None and offset >= base + size:
                # end of a sealed segment: roll into the next
                return self._try_read(sealed_end, max_bytes, False)
            return b"", offset
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            if sealed_end is not None and offset - base + len(chunk) >= size:
                # newline-less tail of a SEALED segment (e.g. sealed by an
                # external writer): it can never complete — skip it with a
                # counter rather than wedging at it forever.  (Rotation in
                # append() newline-terminates before sealing, so this is
                # the defensive path.)
                self.torn_bytes_skipped += len(chunk)
                return self._try_read(sealed_end, max_bytes, False)
            return b"", offset
        complete = chunk[: last_nl + 1]
        return complete, offset + len(complete)

    def _read_compacted(
        self, seg: _Seg, offset: int, max_bytes: int, refresh: bool
    ) -> Optional[Tuple[bytes, int]]:
        assert seg.logical_end is not None
        if offset >= seg.logical_end:
            # at/past the fold's logical end with no later segment visible
            # (the tail normally starts exactly there): nothing to read yet
            return b"", offset
        if offset != seg.base:
            # A byte offset strictly inside the folded range indexes the
            # OLD byte stream; the fold has a different physical layout,
            # so the position is untranslatable.  Restarting at the base
            # re-reads the fold — a last-writer-wins superset of what the
            # reader already applied — hence lossless.
            if not refresh:
                return None
            raise OffsetTruncatedError(
                offset, seg.base, lossless=True,
                reason="inside compacted prefix",
            )
        try:
            with open(seg.path, "rb") as f:
                content = f.read()
        except FileNotFoundError:  # superseded by a newer fold mid-read
            return None
        if not content:
            # everything in the prefix was superseded: continue at the tail
            return self._try_read(seg.logical_end, max_bytes, False)
        return content, seg.logical_end

    def read_from(
        self, offset: int, max_bytes: int = 1 << 24,
        on_truncated: str = "raise",
    ) -> Tuple[List[str], int]:
        """Poll records after `offset`; returns (lines, next_offset).

        Only complete lines are returned; a torn tail (producer mid-append)
        stays unconsumed until its newline lands.  Truncated offsets raise
        ``OffsetTruncatedError`` (see ``read_bytes_from``).
        """
        complete, next_offset = self.read_bytes_from(
            offset, max_bytes, on_truncated=on_truncated
        )
        if not complete:
            return [], next_offset
        return complete.decode("utf-8").splitlines(), next_offset
