"""Replayable ingest journal — the TPU-native stand-in for the reference's
Kafka 0.10 + ZooKeeper model bus (SURVEY.md §2.5).

A topic is an append-only log under a journal directory.  Producers append
model rows (``ALSKafkaProducer.java:29-37`` writes with
``flushOnCheckpoint`` = at-least-once); consumers poll from a byte offset
and commit that offset in their checkpoints, so replay after failure
re-delivers rows — duplicates are tolerated by design because the serving
table is last-writer-wins, exactly like the reference's ``ValueState``
(``ALSKafkaConsumer.java:85-92``).

Topics are SEGMENTED like Kafka's log: the active segment receives
appends; when ``segment_bytes`` is configured, a full segment is sealed
and a new one starts at the current end offset, and ``retain_segments``
bounds disk by deleting the oldest sealed segments.  Offsets are global
byte positions (segment base + position), contiguous across rotation, so
consumer checkpoints are unaffected.  A consumer whose committed offset
has been expired by retention resumes at the earliest retained offset
(Kafka's ``auto.offset.reset=earliest`` semantics) and the skipped byte
count is surfaced on the journal object.

The log format is plain text lines, so journals are interoperable with the
reference's model files and greppable during ops.  Segment files are
``<topic>.log`` (base offset 0) and ``<topic>.log.<base>``.
"""

from __future__ import annotations

import os
import threading
from typing import Iterable, List, Optional, Tuple


class Journal:
    """One topic inside a journal directory."""

    def __init__(
        self,
        journal_dir: str,
        topic: str,
        segment_bytes: Optional[int] = None,
        retain_segments: Optional[int] = None,
    ):
        if not topic or "/" in topic or topic.startswith("."):
            raise ValueError(f"invalid topic name: {topic!r}")
        if segment_bytes is not None and segment_bytes < 1:
            raise ValueError("segment_bytes must be >= 1")
        if retain_segments is not None and retain_segments < 1:
            raise ValueError("retain_segments must be >= 1")
        self.dir = journal_dir
        self.topic = topic
        self.segment_bytes = segment_bytes
        self.retain_segments = retain_segments
        os.makedirs(journal_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, f"{topic}.log")  # base-0 segment
        self._lock = threading.Lock()
        self.expired_bytes_skipped = 0  # consumer-side observability
        self.torn_bytes_skipped = 0     # newline-less tails of sealed segments
        self._seg_cache: Optional[List[Tuple[int, str]]] = None

    # -- segment layout ------------------------------------------------------

    def _segments(self) -> List[Tuple[int, str]]:
        """Sorted [(base_offset, path)] of existing segments."""
        prefix = f"{self.topic}.log"
        out: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return []
        for name in names:
            if name == prefix:
                out.append((0, os.path.join(self.dir, name)))
            elif name.startswith(prefix + "."):
                suffix = name[len(prefix) + 1:]
                try:
                    out.append((int(suffix), os.path.join(self.dir, name)))
                except ValueError:
                    continue  # unrelated file
        out.sort()
        return out

    def _active_segment(self) -> Tuple[int, str]:
        segs = self._segments()
        if not segs:
            return 0, self.path
        return segs[-1]

    def _segments_cached(self, refresh: bool = False) -> List[Tuple[int, str]]:
        """Consumer-side segment list; one os.listdir only when the cache
        is cold, explicitly refreshed, or the topic has no known segments
        (a poll on the hot path must not list the whole journal dir)."""
        if refresh or not self._seg_cache:
            self._seg_cache = self._segments()
        return self._seg_cache

    # -- producer side -------------------------------------------------------

    def append(self, lines: Iterable[str], flush: bool = True) -> int:
        """Append lines; returns the end offset.  ``flush`` fsyncs — the
        analog of the producer's flushOnCheckpoint (at-least-once)."""
        with self._lock:
            base, path = self._active_segment()
            try:
                size = os.path.getsize(path)
            except FileNotFoundError:
                size = 0
            if (
                self.segment_bytes is not None
                and size >= self.segment_bytes
            ):
                # Seal the segment.  Two invariants are established here:
                # (1) durability — sync()/flush=True only reach the ACTIVE
                # segment, so the sealed one must be fsynced now or a crash
                # could drop its page-cache tail while later segments
                # survive; (2) newline termination — a torn tail from a
                # crashed producer can never complete once sealed, so it
                # is terminated into a malformed row the consumer's
                # skip-and-count policy handles, instead of wedging every
                # consumer at a line that never ends.
                with open(path, "rb+") as sf:
                    sf.seek(0, os.SEEK_END)
                    if sf.tell() > 0:
                        sf.seek(-1, os.SEEK_END)
                        if sf.read(1) != b"\n":
                            sf.write(b"\n")
                    sf.flush()
                    os.fsync(sf.fileno())
                    size = sf.tell()
                base = base + size
                path = os.path.join(
                    self.dir, f"{self.topic}.log.{base}"
                )
                self._apply_retention_locked()
            with open(path, "a") as f:
                for line in lines:
                    if "\n" in line:
                        raise ValueError("journal records are single lines")
                    f.write(line)
                    f.write("\n")
                f.flush()
                if flush:
                    os.fsync(f.fileno())
                return base + f.tell()

    def _apply_retention_locked(self) -> None:
        if self.retain_segments is None:
            return
        segs = self._segments()
        # +1: the about-to-be-created active segment counts toward the bound
        excess = len(segs) + 1 - self.retain_segments
        for base, path in segs[:max(excess, 0)]:
            try:
                os.remove(path)
            except OSError:
                pass

    def sync(self) -> None:
        """fsync the active segment without writing — the checkpoint-boundary
        flush for producers appending with ``flush=False``."""
        with self._lock:
            _, path = self._active_segment()
            try:
                with open(path, "a") as f:
                    os.fsync(f.fileno())
            except FileNotFoundError:
                pass

    # -- consumer side -------------------------------------------------------

    def start_offset(self) -> int:
        """Earliest retained offset (0 unless retention expired segments)."""
        segs = self._segments()
        return segs[0][0] if segs else 0

    def end_offset(self) -> int:
        base, path = self._active_segment()
        try:
            return base + os.path.getsize(path)
        except FileNotFoundError:
            return base

    def aligned_end_offset(self) -> int:
        """End offset clamped to the last record boundary: a producer
        mid-append leaves a newline-less tail that ``end_offset`` counts
        but no reader may start inside (consumers seeded at ``latest``
        use this so their first poll is line-aligned)."""
        base, path = self._active_segment()
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                pos = size
                while pos > 0:
                    step = min(1 << 16, pos)
                    f.seek(pos - step)
                    chunk = f.read(step)
                    nl = chunk.rfind(b"\n")
                    if nl >= 0:
                        return base + pos - step + nl + 1
                    pos -= step
        except FileNotFoundError:
            pass
        return base

    def read_bytes_from(
        self, offset: int, max_bytes: int = 1 << 24
    ) -> Tuple[bytes, int]:
        """Poll the raw complete-lines byte chunk after ``offset`` —
        (chunk ending at its last newline, next_offset).  The zero-decode
        variant of ``read_from`` for native bulk ingest.  An offset inside
        an expired segment skips forward to the earliest retained offset
        (counted in ``expired_bytes_skipped``)."""
        out = self._try_read(offset, max_bytes, refresh=False)
        if out is not None and (out[0] or out[1] != offset):
            return out
        # nothing advanced with the cached layout: rescan once — a new
        # segment may have been rolled, or retention may have moved the
        # earliest base — then report whatever the fresh view yields
        out = self._try_read(offset, max_bytes, refresh=True)
        return out if out is not None else (b"", offset)

    def _try_read(
        self, offset: int, max_bytes: int, refresh: bool
    ) -> Optional[Tuple[bytes, int]]:
        segs = self._segments_cached(refresh)
        if not segs:
            return None
        base, path = segs[0]
        for b, p in reversed(segs):
            if offset >= b:
                base, path = b, p
                break
        if offset < base:  # expired by retention: reset to earliest
            self.expired_bytes_skipped += base - offset
            offset = base
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(offset - base)
                chunk = f.read(max_bytes)
        except FileNotFoundError:  # expired between scan and read
            return None
        sealed_end = next(
            (b for b, _ in segs if b > base), None
        )  # this segment is sealed iff a later one exists
        if not chunk:
            if sealed_end is not None and offset >= base + size:
                # end of a sealed segment: roll into the next
                return self._try_read(sealed_end, max_bytes, False)
            return b"", offset
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            if sealed_end is not None and offset - base + len(chunk) >= size:
                # newline-less tail of a SEALED segment (e.g. sealed by an
                # external writer): it can never complete — skip it with a
                # counter rather than wedging at it forever.  (Rotation in
                # append() newline-terminates before sealing, so this is
                # the defensive path.)
                self.torn_bytes_skipped += len(chunk)
                return self._try_read(sealed_end, max_bytes, False)
            return b"", offset
        complete = chunk[: last_nl + 1]
        return complete, offset + len(complete)

    def read_from(self, offset: int, max_bytes: int = 1 << 24) -> Tuple[List[str], int]:
        """Poll records after `offset`; returns (lines, next_offset).

        Only complete lines are returned; a torn tail (producer mid-append)
        stays unconsumed until its newline lands.
        """
        complete, next_offset = self.read_bytes_from(offset, max_bytes)
        if not complete:
            return [], next_offset
        return complete.decode("utf-8").splitlines(), next_offset
