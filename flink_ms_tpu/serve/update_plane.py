"""Sharded online-update plane — co-located SGD workers that close the
train→serve→update loop at fleet scale (ROADMAP item 2).

The reference's online path (``SGD.java``) is a single consumer doing two
queryable-state hops per rating against the fleet; our ``online/sgd.py``
keeps that shape (one MGET per batch) and tops out around ~13k ratings/s
regardless of how many serving shards exist.  This module turns the update
path into an O(shards) plane:

- **Producers** (`UpdatePlaneClient`) hash-route each rating by its
  user-key into one of P durable per-partition input logs
  (``<topic>.upd<p>``, plain journal topics), stamping a contiguous
  per-partition sequence number on every record.  P (default 16, knob
  ``TPUMS_UPDATE_PARTITIONS``) is chosen so that for any fleet size N that
  divides P, partition ``p`` is owned by shard ``p % N`` — and because the
  partition is itself ``fnv1a(user-key) % P``, every user in partition
  ``p`` hashes to serving shard ``p % N`` (``x % P % N == x % N`` whenever
  N | P).  Routing therefore stays aligned with the consumer's
  ``hash % N`` ingest filter across 1→2→4→…-shard topologies with no
  repartitioning.

- **UpdateWorkers** run co-located inside each serving worker process
  (``--updatePlane`` on the sharded worker CLI).  A worker claims the
  partitions it owns via ``flock``ed lease files — the kernel releases the
  lock on any process death, so a SIGKILLed worker's partitions are
  claimable by its sibling replica (or its respawned self) immediately,
  with no stale-lease heuristics.  For each claimed partition it tails the
  input log, batches ratings through the existing vectorized
  ``SGDStep.process_batch`` (v1/v0/bias parity preserved), reading the
  *owned* user vectors straight from the local live ``ModelTable`` (zero
  RPC) and only the cross-shard item vectors remotely — one coalesced MGET
  per batch through a TTL read-through cache.

- **Exactly-once accounting.**  Each applied batch commits ONE line to a
  per-partition apply log (``<topic>.applied<p>``)::

      <seq_from>\t<seq_to>\t<input_offset_after>\t<row|row|...>

  Journal records are single lines, so the commit is atomic under
  SIGKILL: a torn tail is invisible to readers and the batch deterministi-
  cally re-applies.  The emitted rows publish to the model journal *after*
  the commit; a crash inside that window is closed on the next lease
  acquisition by unconditionally re-publishing the LAST apply record's
  rows (idempotent — the serving table is last-writer-wins).  Recovery is
  a single ``tail_line()`` read: resume at ``seq_to``/``input_offset``,
  skip already-applied sequence numbers on replay.  ``audit_partitions``
  proves the property: the apply records' [seq_from, seq_to) ranges must
  exactly tile the submitted range — gaps are lost ratings, overlaps are
  double-applies.

- **Topology awareness.**  Workers carry their registry generation; when
  the serving job observes a newer published generation (a 2→4 cutover by
  ``serve/elastic.py``), the worker finishes its in-flight batch, releases
  its leases and exits — the new generation's workers, already spinning on
  the flocks, take over at the recorded watermarks.  No rating is lost or
  double-applied across the cutover, which the bench's reshard arm and
  ``CHAOS_MODE=update`` both gate on via the sequence audit.

Read-your-writes: each worker keeps an overlay of the rows it published
(so batch k+1 sees batch k's vectors without waiting for the serving
consumer to ingest them — the deterministic analog of the reference's
query-after-publish race), and a visibility probe thread measures the
publish→queryable latency of its own updates against the local table on
the shared ``LATENCY_BUCKETS_S`` ladder
(``tpums_update_visibility_seconds``).

Knobs (env, overridable per-ctor): ``TPUMS_UPDATE_PARTITIONS`` (16),
``TPUMS_UPDATE_BATCH`` (256), ``TPUMS_UPDATE_POLL_S`` (0.02),
``TPUMS_UPDATE_CACHE_TTL_S`` (0.05), ``TPUMS_UPDATE_DIM`` (4, cold-start
mean width), ``TPUMS_UPDATE_LR`` / ``TPUMS_UPDATE_USER_REG`` /
``TPUMS_UPDATE_ITEM_REG`` / ``TPUMS_UPDATE_VERSION`` (SGD hyperparams),
``TPUMS_SGD_BIAS`` (bias mode, shared with online/sgd.py).
"""

from __future__ import annotations

import fcntl
import os
import queue
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core import formats as F
from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from ..online.sgd import SGDStep
from .consumer import ALS_STATE
from .journal import Journal, OffsetTruncatedError
from .sharded import owner_of


# ---------------------------------------------------------------------------
# knobs + layout
# ---------------------------------------------------------------------------

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def default_partitions() -> int:
    return max(1, _env_int("TPUMS_UPDATE_PARTITIONS", 16))


def partition_of(user: int, partitions: int) -> int:
    """Partition of a rating = hash of its USER key — the same FNV-1a the
    consumer's ``hash % N`` ingest filter uses, so partition ``p`` of P is
    owned by shard ``p % N`` for every N dividing P."""
    return owner_of(f"{user}-U", partitions)


def input_topic(topic: str, p: int) -> str:
    return f"{topic}.upd{p}"


def apply_topic(topic: str, p: int) -> str:
    return f"{topic}.applied{p}"


def lease_dir(journal_dir: str, topic: str) -> str:
    return os.path.join(journal_dir, f"{topic}.upd.leases")


def _publish_lock_path(journal_dir: str, topic: str) -> str:
    return os.path.join(journal_dir, f"{topic}.upd.publock")


class _PublishLock:
    """Cross-PROCESS append serialization for the shared model topic.

    Historically the model journal had one producer at a time; the update
    plane is the first place N processes append to it concurrently, and a
    buffered multi-write append could interleave torn lines between
    processes.  An flock around the append (journal's own lock already
    covers threads) restores single-writer framing."""

    def __init__(self, journal_dir: str, topic: str):
        self._path = _publish_lock_path(journal_dir, topic)
        self._lock = threading.Lock()
        self._fd: Optional[int] = None

    def __enter__(self):
        self._lock.acquire()
        if self._fd is None:
            self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o644)
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        try:
            if self._fd is not None:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
        finally:
            self._lock.release()

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


# ---------------------------------------------------------------------------
# producer
# ---------------------------------------------------------------------------

class UpdatePlaneClient:
    """Rating producer: hash-routes submits into the per-partition input
    logs, stamping contiguous per-partition sequence numbers.

    Thread-safe.  Sequence numbers require a SINGLE producer process per
    partition at a time (the rehearsal engine, the chaos producer and the
    bench all share one client); the sequence resumes across restarts from
    the input log's tail line."""

    def __init__(self, journal_dir: str, topic: str,
                 partitions: Optional[int] = None):
        self.journal_dir = journal_dir
        self.topic = topic
        self.partitions = partitions or default_partitions()
        self._lock = threading.Lock()
        self._journals: Dict[int, Journal] = {}
        self._next_seq: Dict[int, int] = {}
        self.submitted = 0

    def _journal(self, p: int) -> Journal:
        j = self._journals.get(p)
        if j is None:
            j = Journal(self.journal_dir, input_topic(self.topic, p))
            tail = j.tail_line()
            self._next_seq[p] = (
                int(tail.split("\t", 1)[0]) + 1 if tail else 0
            )
            self._journals[p] = j
        return j

    def partition_of(self, user: int) -> int:
        return partition_of(user, self.partitions)

    def submit(self, user: int, item: int, rating: float) -> int:
        """Route one rating; returns its partition."""
        p = partition_of(user, self.partitions)
        with self._lock:
            j = self._journal(p)
            seq = self._next_seq[p]
            j.append([f"{seq}\t{user}\t{item}\t{rating!r}"], flush=False)
            self._next_seq[p] = seq + 1
            self.submitted += 1
        return p

    def submit_many(
        self, ratings: List[Tuple[int, int, float]], flush: bool = False
    ) -> int:
        by_p: Dict[int, List[Tuple[int, int, float]]] = {}
        for u, i, r in ratings:
            by_p.setdefault(partition_of(u, self.partitions), []).append(
                (u, i, r)
            )
        with self._lock:
            for p, rs in sorted(by_p.items()):
                j = self._journal(p)
                seq = self._next_seq[p]
                j.append(
                    [f"{seq + k}\t{u}\t{i}\t{r!r}"
                     for k, (u, i, r) in enumerate(rs)],
                    flush=flush,
                )
                self._next_seq[p] = seq + len(rs)
            self.submitted += len(ratings)
        return len(ratings)

    def totals(self) -> Dict[int, int]:
        """Per-partition submitted counts (next sequence numbers)."""
        with self._lock:
            return dict(self._next_seq)

    def sync(self) -> None:
        with self._lock:
            for j in self._journals.values():
                j.sync()


# ---------------------------------------------------------------------------
# watermarks + exactly-once audit
# ---------------------------------------------------------------------------

def submitted_watermarks(journal_dir: str, topic: str,
                         partitions: Optional[int] = None) -> Dict[int, int]:
    """Per-partition count of submitted ratings (tail sequence + 1)."""
    P = partitions or default_partitions()
    out: Dict[int, int] = {}
    for p in range(P):
        tail = Journal(journal_dir, input_topic(topic, p)).tail_line()
        out[p] = int(tail.split("\t", 1)[0]) + 1 if tail else 0
    return out


def applied_watermarks(journal_dir: str, topic: str,
                       partitions: Optional[int] = None) -> Dict[int, int]:
    """Per-partition applied watermark (``seq_to`` of the last commit)."""
    P = partitions or default_partitions()
    out: Dict[int, int] = {}
    for p in range(P):
        tail = Journal(journal_dir, apply_topic(topic, p)).tail_line()
        out[p] = int(tail.split("\t", 2)[1]) if tail else 0
    return out


def _read_all_lines(j: Journal) -> List[str]:
    out: List[str] = []
    off = j.start_offset()
    while True:
        lines, nxt = j.read_from(off, on_truncated="reset")
        if not lines and nxt == off:
            return out
        out.extend(lines)
        off = nxt


def audit_partitions(journal_dir: str, topic: str,
                     partitions: Optional[int] = None) -> dict:
    """Sequence-range audit of the whole plane: for each partition the
    apply records' [seq_from, seq_to) ranges must exactly tile the
    submitted [0, submitted) range.  ``gaps``/``lost`` count ratings never
    applied; ``duplicates`` count ratings covered by more than one commit
    (double-applied).  Meaningful after the plane has drained."""
    P = partitions or default_partitions()
    parts: Dict[int, dict] = {}
    tot = {"submitted": 0, "applied": 0, "duplicates": 0, "gaps": 0,
           "lost": 0}
    for p in range(P):
        submitted = 0
        max_seq = -1
        for ln in _read_all_lines(Journal(journal_dir, input_topic(topic, p))):
            try:
                s = int(ln.split("\t", 1)[0])
            except ValueError:
                continue
            submitted += 1
            if s > max_seq:
                max_seq = s
        ranges: List[Tuple[int, int]] = []
        for ln in _read_all_lines(Journal(journal_dir, apply_topic(topic, p))):
            fields = ln.split("\t", 3)
            try:
                a, b = int(fields[0]), int(fields[1])
            except (ValueError, IndexError):
                continue
            if b > a:
                ranges.append((a, b))
        ranges.sort()
        covered_end = 0
        applied = duplicates = gaps = 0
        for a, b in ranges:
            if a > covered_end:
                gaps += a - covered_end
                applied += b - a
                covered_end = b
            else:
                duplicates += min(b, covered_end) - a
                if b > covered_end:
                    applied += b - covered_end
                    covered_end = b
        lost = max(0, submitted - applied)
        rec = {
            "submitted": submitted,
            "applied": applied,
            "duplicates": duplicates,
            "gaps": gaps,
            "lost": lost,
            "contiguous_input": max_seq + 1 == submitted,
        }
        parts[p] = rec
        for k in tot:
            tot[k] += rec[k]
    tot["partitions"] = parts
    tot["clean"] = tot["duplicates"] == 0 and tot["lost"] == 0
    return tot


# ---------------------------------------------------------------------------
# visibility probe
# ---------------------------------------------------------------------------

class _VisibilityProbe(threading.Thread):
    """Measures read-your-writes latency: the worker enqueues (key,
    expected payload) right after publishing; this thread polls the LOCAL
    serving table until the row lands and observes publish→queryable
    seconds on the shared latency ladder.  Sheds to the newest probes
    under backlog — it measures, it never backpressures."""

    def __init__(self, table, hist, poll_s: float = 0.002,
                 timeout_s: float = 5.0):
        super().__init__(daemon=True, name="tpums-update-visprobe")
        self._table = table
        self._hist = hist
        self._poll_s = poll_s
        self._timeout_s = timeout_s
        self._q: "queue.Queue" = queue.Queue(maxsize=256)
        self._stop = threading.Event()
        self.observed = 0
        self.timeouts = 0
        self.shed = 0
        self.last_visibility_s: Optional[float] = None

    def enqueue(self, key: str, payload: str,
                tid: Optional[str] = None,
                psid: Optional[str] = None) -> None:
        try:
            self._q.put_nowait(
                (time.monotonic(), key, payload, tid, psid, time.time()))
        except queue.Full:
            self.shed += 1

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                t0, key, expected, tid, psid, t0_wall = self._q.get(
                    timeout=0.2)
            except queue.Empty:
                continue
            deadline = t0 + self._timeout_s
            hit = False
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    if self._table.get(key) == expected:
                        hit = True
                        break
                except Exception:
                    break
                if self._q.qsize() > 64:
                    # deep backlog: shed this probe, keep up with the newest
                    self.shed += 1
                    break
                time.sleep(self._poll_s)
            if hit:
                dt = time.monotonic() - t0
                self.last_visibility_s = dt
                self._hist.observe(dt)
                self.observed += 1
                if tid:
                    # closes the apply -> publish -> visible chain: same
                    # tid, parented under the batch's apply span
                    obs_tracing.span_event(
                        "update_visible", tid=tid, psid=psid, t0=t0_wall,
                        dur_s=round(dt, 9), key=key)
            elif time.monotonic() >= deadline:
                self.timeouts += 1


# ---------------------------------------------------------------------------
# the co-located worker
# ---------------------------------------------------------------------------

class _Part:
    __slots__ = ("p", "in_j", "app_j", "fd", "next_seq", "in_off")

    def __init__(self, p, in_j, app_j, fd, next_seq, in_off):
        self.p = p
        self.in_j = in_j
        self.app_j = app_j
        self.fd = fd
        self.next_seq = next_seq
        self.in_off = in_off


class UpdateWorker:
    """Per-shard SGD update worker.

    Co-located mode (``job=`` a running ServingJob): owned user vectors
    read from the live local table, topology generation observed through
    the job's heartbeat.  Standalone mode (``table=`` or nothing): used by
    tests and the profile tool.  Either way the worker claims its owned
    partitions (``p % num_workers == worker_index``) via flock leases, so
    replicas of the same shard contend safely and exactly one applies."""

    def __init__(
        self,
        journal_dir: str,
        topic: str,
        worker_index: int,
        num_workers: int,
        *,
        job=None,
        table=None,
        client_factory: Optional[Callable[[], object]] = None,
        model_journal: Optional[Journal] = None,
        partitions: Optional[int] = None,
        batch_size: Optional[int] = None,
        poll_s: Optional[float] = None,
        cache_ttl_s: Optional[float] = None,
        learning_rate: Optional[float] = None,
        user_reg: Optional[float] = None,
        item_reg: Optional[float] = None,
        version: Optional[str] = None,
        update_bias: Optional[bool] = None,
        generation: Optional[int] = None,
        state: str = ALS_STATE,
        dim: Optional[int] = None,
        visibility_probe: bool = True,
    ):
        self.journal_dir = journal_dir
        self.topic = topic
        self.worker_index = worker_index
        self.num_workers = num_workers
        self._job = job
        self._table = table if table is not None else (
            getattr(job, "table", None) if job is not None else None
        )
        self.client_factory = client_factory
        self.partitions = partitions or default_partitions()
        self.batch_size = batch_size or max(
            1, _env_int("TPUMS_UPDATE_BATCH", 256))
        self.poll_s = poll_s if poll_s is not None else _env_float(
            "TPUMS_UPDATE_POLL_S", 0.02)
        self.cache_ttl_s = cache_ttl_s if cache_ttl_s is not None else (
            _env_float("TPUMS_UPDATE_CACHE_TTL_S", 0.05))
        self.lr = learning_rate if learning_rate is not None else (
            _env_float("TPUMS_UPDATE_LR", 0.1))
        self.user_reg = user_reg if user_reg is not None else (
            _env_float("TPUMS_UPDATE_USER_REG", 0.0))
        self.item_reg = item_reg if item_reg is not None else (
            _env_float("TPUMS_UPDATE_ITEM_REG", 0.0))
        self.version = version or os.environ.get("TPUMS_UPDATE_VERSION", "v1")
        self.update_bias = update_bias if update_bias is not None else (
            os.environ.get("TPUMS_SGD_BIAS", "").lower()
            in ("1", "true", "yes")
        )
        self.generation = generation
        self.state = state
        self.dim = dim or _env_int("TPUMS_UPDATE_DIM", 4)

        self._model_journal = model_journal or Journal(journal_dir, topic)
        self._pub_lock = _PublishLock(journal_dir, topic)
        self._lease_dir = lease_dir(journal_dir, topic)
        self._owned = [
            p for p in range(self.partitions)
            if p % num_workers == worker_index
        ]
        self._held: Dict[int, _Part] = {}
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._client = None
        self._client_retry_at = 0.0
        self._step: Optional[SGDStep] = None
        self._overlay: Dict[str, str] = {}
        self._cache: Dict[str, Tuple[Optional[str], float]] = {}
        self._last_reads: Dict[str, Optional[str]] = {}
        self._recording = False
        # co-located arena: swap factor bytes in place with a native CAS
        # against the value this batch READ, instead of re-putting whole
        # rows — a failed CAS (value drifted under us) falls back to the
        # LWW re-put.  TPUMS_ARENA_CAS=0 keeps the re-put path.
        self._cas_enabled = os.environ.get("TPUMS_ARENA_CAS", "1") != "0"
        self.stats = {
            "applied": 0, "batches": 0, "conflicts": 0, "replayed_rows": 0,
            "remote_keys": 0, "cache_hits": 0, "local_hits": 0,
            "published_rows": 0,
        }

        reg = obs_metrics.get_registry()
        self._c_updates = reg.counter(
            "tpums_update_updates_total", state=state)
        self._c_conflicts = reg.counter(
            "tpums_update_conflict_retries_total", state=state)
        self._c_batches = reg.counter(
            "tpums_update_batches_total", state=state)
        self._h_vis = reg.histogram(
            "tpums_update_visibility_seconds",
            bounds=obs_metrics.LATENCY_BUCKETS_S, state=state)
        self._probe: Optional[_VisibilityProbe] = None
        if visibility_probe and self._table is not None and hasattr(
                self._table, "get"):
            self._probe = _VisibilityProbe(self._table, self._h_vis)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "UpdateWorker":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"tpums-update-w{self.worker_index}",
        )
        if self._probe is not None:
            self._probe.start()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._probe is not None:
            self._probe.stop()

    @property
    def drained(self) -> bool:
        return self._drained.is_set()

    def wait_drained(self, timeout_s: float = 30.0) -> bool:
        return self._drained.wait(timeout_s)

    @property
    def held_partitions(self) -> List[int]:
        return sorted(self._held)

    # -- leases + recovery ---------------------------------------------------

    def _try_acquire(self, p: int) -> Optional[_Part]:
        os.makedirs(self._lease_dir, exist_ok=True)
        path = os.path.join(self._lease_dir, f"p{p}.lock")
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return None
        # owner info is observability only — the flock IS the lease, and
        # the kernel releases it the instant the holder dies
        try:
            os.ftruncate(fd, 0)
            os.write(fd, (
                f"pid={os.getpid()} worker={self.worker_index}"
                f" gen={self.generation}\n").encode())
        except OSError:
            pass
        in_j = Journal(self.journal_dir, input_topic(self.topic, p))
        app_j = Journal(self.journal_dir, apply_topic(self.topic, p))
        tail = app_j.tail_line()
        if tail:
            fields = tail.split("\t", 3)
            next_seq, in_off = int(fields[1]), int(fields[2])
            # close the commit→publish crash window: the last commit's
            # rows may never have reached the model journal — re-publish
            # them unconditionally (last-writer-wins makes this idempotent)
            rows = fields[3].split("|") if len(fields) > 3 and fields[3] \
                else []
            self._publish(rows)
            self.stats["replayed_rows"] += len(rows)
        else:
            next_seq, in_off = 0, in_j.start_offset()
        return _Part(p, in_j, app_j, fd, next_seq, in_off)

    def _acquire_owned(self) -> None:
        for p in self._owned:
            if p in self._held or self._stop.is_set():
                continue
            part = self._try_acquire(p)
            if part is not None:
                self._held[p] = part

    def _release_all(self) -> None:
        for part in self._held.values():
            try:
                os.close(part.fd)  # closes => kernel drops the flock
            except OSError:
                pass
        self._held.clear()

    # -- lookups -------------------------------------------------------------

    def _ensure_client(self):
        if self._client is not None:
            return self._client
        if self.client_factory is None:
            return None
        now = time.monotonic()
        if now < self._client_retry_at:
            return None
        try:
            self._client = self.client_factory()
        except Exception as e:
            print(f"[update-plane] client unavailable: {e}", file=sys.stderr)
            self._client_retry_at = now + 1.0
            return None
        return self._client

    def _drop_client(self) -> None:
        try:
            if self._client is not None and hasattr(self._client, "close"):
                self._client.close()
        except Exception:
            pass
        self._client = None
        self._client_retry_at = time.monotonic() + 0.5

    def _remote_fetch(self, keys: List[str]) -> List[Optional[str]]:
        cli = self._ensure_client()
        if cli is None:
            return [None] * len(keys)
        try:
            vals = cli.query_states(self.state, keys)
            self.stats["remote_keys"] += len(keys)
            return list(vals)
        except Exception as e:
            print(f"[update-plane] remote MGET failed for {len(keys)} keys:"
                  f" {e}", file=sys.stderr)
            self._drop_client()
            return [None] * len(keys)

    def _lookup_many(self, keys: List[str]) -> List[Optional[str]]:
        """Overlay (read-your-writes) → local live table for owned keys →
        TTL read-through cache → one coalesced remote MGET for the rest."""
        now = time.monotonic()
        out: List[Optional[str]] = [None] * len(keys)
        misses: List[Tuple[int, str]] = []
        for idx, key in enumerate(keys):
            ov = self._overlay.get(key)
            if ov is not None:
                out[idx] = ov
                continue
            if self._table is not None and owner_of(
                    key, self.num_workers) == self.worker_index:
                try:
                    payload = self._table.get(key)
                except Exception:
                    payload = None
                out[idx] = payload
                self.stats["local_hits"] += 1
                if self._recording:
                    self._last_reads[key] = payload
                continue
            ent = self._cache.get(key)
            if ent is not None and now - ent[1] <= self.cache_ttl_s:
                out[idx] = ent[0]
                self.stats["cache_hits"] += 1
                continue
            misses.append((idx, key))
        if misses:
            vals = self._remote_fetch([k for _, k in misses])
            if len(self._cache) > 16384:
                self._cache.clear()
            for (idx, key), v in zip(misses, vals):
                out[idx] = v
                self._cache[key] = (v, now)
        return out

    def _lookup_one(self, key: str) -> Optional[str]:
        return self._lookup_many([key])[0]

    def _ensure_step(self) -> SGDStep:
        if self._step is not None:
            return self._step
        zero = ";".join(["0.0"] * self.dim)
        user_mean = self._lookup_one("MEAN-U") or zero
        item_mean = self._lookup_one("MEAN-I") or zero
        self._step = SGDStep(
            self._lookup_one,
            user_mean,
            item_mean,
            learning_rate=self.lr,
            user_reg=self.user_reg,
            item_reg=self.item_reg,
            version=self.version,
            lookup_many=self._lookup_many,
            update_bias=self.update_bias,
        )
        return self._step

    # -- apply path ----------------------------------------------------------

    def _publish(self, rows: List[str]) -> None:
        if not rows:
            return
        with self._pub_lock:
            self._model_journal.append(rows, flush=False)
        self.stats["published_rows"] += len(rows)

    def _conflict_pass(self, batch, rows: List[str]) -> List[str]:
        """Optimistic concurrency for the LOCALLY read item vectors: if
        concurrent ingest changed an item row between our base read and
        the apply, recompute that item's ratings against the fresh vector
        and APPEND the rows — last-writer-wins makes the recomputed rows
        land.  Remote (cross-shard) conflicts are not detectable here and
        keep the reference's at-least-once LWW semantics."""
        if self._table is None or not self._last_reads:
            return rows
        extra: List[str] = []
        by_item: Optional[Dict[int, list]] = None
        checked = set()
        for _, item, _ in batch:
            key = f"{item}-I"
            if key in checked or key not in self._last_reads:
                continue
            checked.add(key)
            try:
                cur = self._table.get(key)
            except Exception:
                continue
            if cur == self._last_reads[key]:
                continue
            self._c_conflicts.inc()
            self.stats["conflicts"] += 1
            # make the recompute see the fresh row, not our stale copies
            self._overlay.pop(key, None)
            self._cache.pop(key, None)
            if by_item is None:
                by_item = {}
                for u2, i2, r2 in batch:
                    by_item.setdefault(i2, []).append((u2, i2, r2))
            self._recording = False
            try:
                step = self._ensure_step()
                for u2, i2, r2 in by_item.get(item, ()):
                    extra.extend(step.process(u2, i2, r2))
            finally:
                self._recording = True
        return rows + extra

    def _apply_batch(self, part: _Part, batch, seq_from: int,
                     in_off_after: int) -> None:
        # sampled trace root: apply -> publish -> visible is the update
        # plane's critical chain, and TPUMS_TRACE_SAMPLE decides which
        # batches leave spans behind
        tid = obs_tracing.sample_trace()
        apply_sid = obs_tracing.new_span_id() if tid else None
        t_apply0 = time.time()
        step = self._ensure_step()
        self._last_reads = {}
        self._recording = True
        try:
            rows = step.process_batch(batch)
        finally:
            self._recording = False
        rows = self._conflict_pass(batch, rows)
        seq_to = seq_from + len(batch)
        # ONE line = the atomic commit point (torn tails are invisible to
        # journal readers, so a SIGKILL mid-write re-applies the batch)
        part.app_j.append(
            [f"{seq_from}\t{seq_to}\t{in_off_after}\t" + "|".join(rows)],
            flush=False,
        )
        if tid:
            obs_tracing.span_event(
                "update_apply", tid=tid, sid=apply_sid, psid=None,
                t0=t_apply0, dur_s=round(time.time() - t_apply0, 9),
                worker=self.worker_index, updates=len(batch),
                rows=len(rows))
            t_pub0 = time.time()
        self._publish(rows)
        if tid:
            obs_tracing.span_event(
                "update_publish", tid=tid, psid=apply_sid, t0=t_pub0,
                dur_s=round(time.time() - t_pub0, 9),
                worker=self.worker_index, rows=len(rows))
        # co-located arena table: seqlock-update the shared rows in place
        # right now — update -> queryable visibility stops round-tripping
        # through the journal (the journal stays the durability source;
        # the consume loop's later LWW replay of these same rows is a
        # no-op rewrite).  Safe because the worker holds the table OBJECT
        # (and with it the arena's writer flock), never a second mapping.
        direct = getattr(self._table, "kind", "") == "arena"
        direct_keys: List[str] = []
        direct_vals: List[str] = []
        probe_key = probe_payload = None
        for row in rows:
            try:
                id_, typ, vec_s = row.split(",", 2)
            except ValueError:
                continue
            key = f"{id_}-{typ}"
            self._overlay[key] = vec_s
            if direct:
                direct_keys.append(key)
                direct_vals.append(vec_s)
            if typ == F.USER and owner_of(
                    key, self.num_workers) == self.worker_index:
                probe_key, probe_payload = key, vec_s
        if direct and direct_keys:
            if self._cas_enabled and hasattr(self._table,
                                             "cas_many_columns"):
                # expected = the value each update step READ; a mismatch
                # means another writer got there first and the journal's
                # LWW replay is the truth — re-put only the failures
                expected = [self._last_reads.get(k) for k in direct_keys]
                failed = self._table.cas_many_columns(
                    direct_keys, expected, direct_vals)
                if failed:
                    self._table.put_many_columns(
                        [direct_keys[i] for i in failed],
                        [direct_vals[i] for i in failed])
            else:
                self._table.put_many_columns(direct_keys, direct_vals)
        if len(self._overlay) > 65536:
            self._overlay.clear()
        part.next_seq = seq_to
        part.in_off = in_off_after
        self._c_updates.inc(len(batch))
        self._c_batches.inc()
        self.stats["applied"] += len(batch)
        self.stats["batches"] += 1
        if self._probe is not None and probe_key is not None:
            self._probe.enqueue(probe_key, probe_payload,
                                tid=tid, psid=apply_sid)

    def _drain_part(self, part: _Part) -> bool:
        before = part.in_off
        try:
            lines, next_off = part.in_j.read_from(
                part.in_off, max_bytes=1 << 20)
        except OffsetTruncatedError as e:
            part.in_off = e.resume_offset
            return True
        if not lines:
            part.in_off = next_off
            return next_off != before
        off = part.in_off
        batch: List[Tuple[int, int, float]] = []
        batch_from = 0
        applied_any = False
        for ln in lines:
            line_end = off + len(ln.encode("utf-8")) + 1
            rec = None
            try:
                s_seq, s_u, s_i, s_r = ln.split("\t")
                rec = (int(s_seq), int(s_u), int(s_i), float(s_r))
            except ValueError:
                pass  # malformed row: skip-and-continue, like the consumer
            if rec is not None and rec[0] >= part.next_seq:
                seq = rec[0]
                if batch and seq != batch_from + len(batch):
                    # producer-side discontinuity: commit what we have so
                    # the apply record's range stays exact, then let the
                    # audit surface the gap
                    self._apply_batch(part, batch, batch_from, off)
                    applied_any = True
                    batch = []
                if not batch:
                    batch_from = seq
                batch.append(rec[1:])
                if len(batch) >= self.batch_size:
                    self._apply_batch(part, batch, batch_from, line_end)
                    applied_any = True
                    batch = []
            off = line_end
            if self._stop.is_set() and not batch:
                break
        if batch:
            self._apply_batch(part, batch, batch_from, off)
            applied_any = True
        if not applied_any:
            # everything in the chunk was replay/malformed: advance past it
            part.in_off = next_off
        return applied_any or part.in_off != before

    # -- topology ------------------------------------------------------------

    def _gen_superseded(self) -> bool:
        if self.generation is None:
            return False
        observed = None
        if self._job is not None:
            observed = getattr(self._job, "_observed_topology_gen", None)
        if observed is None:
            return False
        return observed > self.generation

    # -- main loop -----------------------------------------------------------

    def _run(self) -> None:
        last_acquire = -1.0
        try:
            while not self._stop.is_set():
                if self._gen_superseded():
                    # a newer generation was published: finish, release the
                    # leases and let its workers resume at our watermarks
                    break
                now = time.monotonic()
                if not self._held or now - last_acquire >= max(
                        self.poll_s, 0.05):
                    self._acquire_owned()
                    last_acquire = now
                progress = False
                for part in list(self._held.values()):
                    try:
                        progress |= self._drain_part(part)
                    except Exception as e:
                        # one poisoned partition must not kill the plane
                        print(f"[update-plane] partition {part.p} error:"
                              f" {e}", file=sys.stderr)
                    if self._stop.is_set():
                        break
                if not progress:
                    self._stop.wait(self.poll_s)
        finally:
            self._release_all()
            self._pub_lock.close()
            self._drop_client()
            self._drained.set()


# ---------------------------------------------------------------------------
# serving-worker attachment (the --updatePlane flag of serve/sharded.py)
# ---------------------------------------------------------------------------

def attach_update_worker(job, params, worker_index: int,
                         num_workers: int) -> UpdateWorker:
    """Build + start the co-located UpdateWorker for a serving worker
    process.  Remote (cross-shard) reads resolve through whatever fleet
    client the deployment shape provides: the elastic client when the
    worker runs under a topology group, the HA sharded client under a
    plain replicated job group, else no remote reads (mean fallback)."""
    topology_group = params.get("topologyGroup")
    job_group = params.get("jobGroup")

    def client_factory():
        if topology_group:
            from .elastic import ElasticClient
            return ElasticClient(
                topology_group, timeout_s=5.0, resolve_timeout_s=2.0)
        if job_group:
            from .ha import HAShardedClient
            return HAShardedClient(
                num_workers, job_group=job_group, timeout_s=5.0)
        return None

    worker = UpdateWorker(
        job.journal.dir,
        job.journal.topic,
        worker_index,
        num_workers,
        job=job,
        client_factory=client_factory,
        generation=params.get_int("topologyGen", None),
        partitions=params.get_int("updatePartitions", None),
        batch_size=params.get_int("updateBatch", None),
    )
    return worker.start()
