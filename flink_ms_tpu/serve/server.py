"""Lookup server — the serving data plane, counterpart of Flink's Netty
KvState server queried by ``QueryClientHelper.queryState``
(``QueryClientHelper.java:104-139``).

Line protocol over TCP (persistent connections, thread per client):

    request:  ``GET\\t<state_name>\\t<key>\\n``
              ``MGET\\t<state_name>\\t<k1>,<k2>,...\\n``  (batched point gets)
              ``TOPK\\t<state_name>\\t<user_id>\\t<k>\\n``  (device-scored top-k)
              ``TOPKV\\t<state_name>\\t<k>\\t<f1;f2;...>\\n``  (top-k by an
                                  explicit query vector — lets a sharded
                                  client fan out across workers that only
                                  hold a slice of the catalog)
              ``COUNT\\t<state_name>\\n``  (key count — ops/metrics surface
                                  and multi-process ingest barrier)
              ``HEALTH\\t<state_name>\\n``  (liveness/readiness: state name,
                                  key count, ingest backlog, replaying-vs-
                                  ready — the HA plane's supervisor and
                                  load-balancer surface, serve/ha.py)
              ``DOT\\t<state_name>\\t<range>\\t<fid>:<val>;...\\n``  (server-
                                  side sparse dot against range-partitioned
                                  SVM rows: the whole sparse query in ONE
                                  round trip, no bucket payloads shipped or
                                  parsed client-side — realizing the intent
                                  of the reference's range partitioning,
                                  RangePartitionSVMPredict.java:63,80-101,
                                  which still pays one RPC per bucket)
              ``METRICS\\n``  (process-wide observability snapshot — every
                                  counter/gauge/histogram the obs/ registry
                                  holds, as one JSON line; the Prometheus
                                  text rendering of the same snapshot is a
                                  client-side transform, obs/scrape.py)
              ``PING\\n``
    response: ``V\\t<value>\\n``   key found / top-k payload ``item:score;...``
              ``N\\n``            unknown key (client maps to Optional.empty,
                                  mirroring UnknownKeyOrNamespace handling)
              ``M\\t<i1>\\t<i2>...\\n``  MGET reply, one item per key in
                                  request order: ``N`` missing, ``V<value>``
                                  found (values are tab-free by contract —
                                  model rows are CSV/semicolon text)
              ``E\\t<msg>\\n``    error (unknown state name, bad request)
              ``C\\t<n>\\n``      COUNT reply
              ``H\\t<json>\\n``   HEALTH reply (single-line JSON object)
              ``D\\t<dot>\\t<missing_buckets_csv>\\n``  DOT reply: float64
                                  repr of the partial dot over buckets
                                  present in the state; buckets with no
                                  row listed so clients can keep the
                                  reference's missing-range console output
              ``J\\t<json>\\n``   METRICS reply (single-line JSON snapshot)
              ``PONG\\t<job_id>\\t<state_name>\\n``

Tracing (obs/tracing.py): any request MAY carry a trailing ``tid=<id>``
tab field; the server strips it before verb dispatch (handlers see the
seed protocol's exact field counts), records a ``server_reply`` span
event (verb, latency, and — for microbatched top-k — queue wait, batch
size, device seconds) and echoes ``tid=<id>`` back on the reply line.
Untraced traffic is byte-identical to the seed protocol in both
directions; the C++ native plane answers ``E`` to traced requests
(documented, not parity-tested — tracing targets the Python plane).

Wire protocol v2 (``serve/proto.py``): a client may send the text line
``HELLO\\tB2`` to switch the connection to length-prefixed binary batch
frames — one frame of packed verb records in, one frame of reply records
out, records answered in order and a whole frame submitted to the top-k
microbatcher before any reply is resolved.  Old clients never send HELLO
and stay byte-identical on the wire (pinned by
``tests/test_native_protocol.py``); the C++ native plane speaks the same
negotiation and framing.

The batched verb exists to beat the reference's serving hot spot: its online
SGD pays two Netty round trips per rating (SGD.java:172-173) and its MSE job
one per rating plus one per user group (MSE.java:129-158); MGET folds each
of those into a single round trip.

TOPK/TOPKV additionally ride a server-internal CROSS-REQUEST MICROBATCHER
(``microbatch.py``): concurrent top-k queries — from many connections, or
from one connection's pipelined in-flight window — coalesce into ONE
batched matmul + ``top_k`` device dispatch instead of serializing on the
index lock, reading the catalog once per dispatch rather than once per
query.  Knobs: ``TPUMS_TOPK_BATCH`` (default on; ``0`` disables),
``TPUMS_TOPK_BATCH_MAX`` (queries per dispatch, default 32),
``TPUMS_TOPK_BATCH_WAIT_US`` (coalescing window, default 200 — the
worst-case extra latency a lone request pays).  The wire protocol is
UNCHANGED: batching never reorders a connection's replies, and a lone
query runs the exact single-query program, so the native plane's
byte-parity contract below is untouched.

Behind the verbs sits the two-tier RETRIEVAL PLANE (round 11, see
``topk.py``/``ann.py``): ``TPUMS_TOPK_TIER`` (``exact``/``ivf``/``auto``)
selects brute-force vs IVF-ANN scoring, ``TPUMS_TOPK_SHARDED`` /
``TPUMS_TOPK_SHARD_MIN_ROWS`` control the mesh-sharded exact layout, and
``TPUMS_ANN_NLIST`` / ``TPUMS_ANN_NPROBE`` / ``TPUMS_ANN_RECALL_MIN``
size and gate the ANN tier.  All tiers answer through the same
TOPK/TOPKV wire surface with exact scores for every returned item.

A C++ epoll implementation of the same protocol
(``native/lookup_server.cpp``, wrapped by
``native_store.NativeLookupServer``, enabled with ``--nativeServer true`` on
the rocksdb backend) serves the full verb set straight from the persistent
store, including catalog-scored TOPK/TOPKV (round 4); this Python server is
the default and the semantics contract — the native plane's replies are
byte-parity-tested against it.
"""

from __future__ import annotations

import socketserver
import threading
import time
from typing import Dict, Optional

from ..core.formats import RangePayloadCache, gather_sorted, sort_dedup_last
from ..obs import metrics as obs_metrics
from ..obs import profiler as obs_profiler
from ..obs import tracing as obs_tracing
from . import admission as admission_ctl
from . import proto
from . import push as push_plane
from .table import ModelTable


class _ConnPushSink:
    """Per-connection ordered write gate shared by the reply writer and
    the push engine (serve/push.py).

    Replies and unsolicited PUSH frames leave through ONE lock, so engine
    writes never interleave bytes with a reply write.  ``arm()`` (called
    by the engine while a subscribe/resume reply is still pending) flips
    pushes into a deferred buffer that ``write_reply`` flushes right
    after the reply bytes — a delta can therefore never overtake its own
    S/R baseline on the wire.  Pull-only connections pay one uncontended
    lock acquisition per reply burst and write byte-identical output."""

    __slots__ = ("_wfile", "_binary", "_lock", "_deferred", "used")

    def __init__(self, wfile, binary: bool):
        self._wfile = wfile
        self._binary = binary
        self._lock = threading.Lock()
        self._deferred = None
        self.used = False  # a push verb bound subscriptions to this conn

    def arm(self) -> None:
        with self._lock:
            if self._deferred is None:
                self._deferred = []

    def defer(self, texts) -> None:
        with self._lock:
            if self._deferred is None:
                self._deferred = []
            self._deferred.extend(texts)

    def send_push(self, text: str) -> None:
        with self._lock:
            if self._deferred is not None:
                self._deferred.append(text)
                return
            self._write(text)

    def _write(self, text: str) -> None:
        if self._binary:
            self._wfile.write(proto.encode_reply_frame([text]))
        else:
            self._wfile.write((text + "\n").encode("utf-8"))

    def write_reply(self, data: bytes) -> None:
        """Reply bytes, then any deferred pushes, one critical section."""
        with self._lock:
            self._wfile.write(data)
            deferred, self._deferred = self._deferred, None
            if deferred:
                for text in deferred:
                    self._write(text)


class _DeferredReply:
    """A reply whose value is still in flight in the top-k microbatcher.
    ``resolve()`` parks until the dispatcher scatters the result back and
    renders the same wire reply the synchronous path would have.

    ``post`` (set by ``_dispatch_async``) runs at resolve time — that is
    the only moment a deferred verb's true latency is known, so metric
    observation, span events and the tid echo all live there; it receives
    the rendered reply plus the resolver (whose ``pending`` attribute,
    when present, carries the microbatcher's span fields)."""

    __slots__ = ("_resolver", "post")

    def __init__(self, resolver):
        self._resolver = resolver
        self.post = None

    def resolve(self) -> str:
        try:
            payload = self._resolver()
        except Exception as e:
            reply = f"E\ttopk failed: {e}"
        else:
            reply = "N" if payload is None else f"V\t{payload}"
        if self.post is not None:
            reply = self.post(reply, self._resolver)
        return reply


class LookupServer:
    def __init__(
        self,
        tables: Dict[str, ModelTable],
        host: str = "0.0.0.0",
        port: int = 6123,
        job_id: str = "local",
        topk_handlers: Optional[Dict[str, object]] = None,
        health_fn=None,
        admission: Optional[admission_ctl.AdmissionController] = None,
        staleness_fn=None,
    ):
        self.tables = tables
        self.job_id = job_id
        self.topk_handlers = topk_handlers or {}
        # per-read staleness provider (serve/georepl.py): a callable ->
        # seconds this server's state trails its home region, or None on
        # a non-follower.  Only consulted for requests that opted in via
        # the ``st=`` wire field — untagged traffic never pays the call.
        self.staleness_fn = staleness_fn
        # per-tenant admission control (serve/admission.py): None unless a
        # TPUMS_ADMIT_* rate knob is set (or a controller is injected) —
        # the admission-off hot path costs one attribute check
        self.admission = (admission if admission is not None
                          else admission_ctl.AdmissionController.from_env())
        # HEALTH verb provider: a callable -> dict describing the owning
        # job's liveness (ServingJob.health).  A bare server (tests, ad-hoc
        # tables) synthesizes a minimal always-ready report instead.
        self.health_fn = health_fn
        # DOT verb caches: per-payload parse cache (payload-string-keyed =
        # coherent by construction) feeding a per-state merged sorted index
        # keyed on the table's mutation version
        self._dot_cache = RangePayloadCache()
        self._dot_merged: Dict[str, tuple] = {}
        self._dot_build_lock = threading.Lock()
        self.requests = 0  # observability; also lets tests assert round trips
        # per-verb instrument cache: (requests counter, latency histogram,
        # error counter), created lazily so only verbs actually served
        # appear in the exposition
        self._obs_verbs: Dict[str, tuple] = {}
        self._obs_burst = obs_metrics.get_registry().histogram(
            "tpums_server_burst_size", bounds=obs_metrics.SIZE_BUCKETS)
        # live persistent connections + their handler threads: clients hold
        # sockets open across many requests, so TCPServer.shutdown() alone
        # leaves handlers serving AFTER stop() returns — the round-3 long
        # soak caught a handler reading the native store after the owning
        # job closed it (tpums I/O failure; a use-after-close)
        self._conns: set = set()
        self._conn_threads: set = set()
        self._conn_lock = threading.Lock()
        # push plane (serve/push.py): built lazily on the FIRST subscribe
        # — constructing the engine registers table change listeners,
        # which forces the consumer's Python ingest path (same trade the
        # top-k dirty set makes), so pull-only deployments never pay it
        self._push_engine: Optional[push_plane.PushEngine] = None
        self._push_create_lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                """Line loop with explicit framing (not rfile.readline):
                after blocking for the first request, every further
                COMPLETE line already buffered or immediately readable is
                drained into the same burst, and the burst's TOPK/TOPKV
                queries are all submitted to the microbatcher BEFORE any
                reply is awaited — so a pipelined client's in-flight
                window coalesces into one batched dispatch exactly like
                concurrent connections do.  Replies keep strict request
                order (the wire contract is unchanged)."""
                import select

                with outer._conn_lock:
                    outer._conns.add(self.connection)
                    outer._conn_threads.add(threading.current_thread())
                sock = self.connection
                buf = bytearray()
                eof = False
                # tenant bound to THIS connection by an extended HELLO
                # (``HELLO\tB2\ttn=<t>``) — the B2 record layout has no
                # room for a per-request field, so on the binary plane
                # tenancy is a connection property.  ``tr=1`` likewise
                # binds per-record tracing: every subsequent request
                # record carries one extra trailing tid field.
                conn_tenant = None
                conn_trace = False
                conn_stale = False  # ``st=1``: staleness on every reply
                conn_push = False   # ``su=1``: B2 push frames accepted
                # one ordered write gate per connection: replies and any
                # engine pushes share it (tab SUBSCRIBE is its own opt-in
                # — sending the verb marks the connection push-capable,
                # so the tab sink is always offered to dispatch)
                sink = _ConnPushSink(self.wfile, binary=False)
                try:
                    while True:
                        # block for at least one complete line (or EOF)
                        while not eof and buf.find(b"\n") < 0:
                            try:
                                chunk = sock.recv(65536)
                            except (ConnectionResetError, OSError):
                                return
                            if not chunk:
                                eof = True
                                break
                            buf += chunk
                        # opportunistic non-blocking drain: whatever the
                        # client already put on the wire joins this burst
                        while not eof:
                            try:
                                readable, _, _ = select.select(
                                    [sock], [], [], 0)
                            except (OSError, ValueError):
                                break
                            if not readable:
                                break
                            try:
                                chunk = sock.recv(65536)
                            except (ConnectionResetError, OSError):
                                chunk = b""
                            if not chunk:
                                eof = True
                                break
                            buf += chunk
                        lines = []
                        hello = False
                        while True:
                            nl = buf.find(b"\n")
                            if nl < 0:
                                break
                            raw = bytes(buf[:nl])
                            del buf[:nl + 1]
                            lines.append(raw.decode("utf-8"))
                            hello_b = proto.HELLO_LINE.encode("utf-8")
                            if raw == hello_b or raw.startswith(
                                    hello_b + b"\t"):
                                # candidate protocol switch: only a HELLO
                                # whose every extension parses (tn=/tr=)
                                # flips the connection — anything else
                                # stays a normal line and answers the
                                # generic E\tbad request below, exactly
                                # like an old server.
                                ext = proto.parse_hello(
                                    raw.decode("utf-8").split("\t"))
                                if ext is not None:
                                    # whatever follows the HELLO line is
                                    # already B2 frames — stop
                                    # line-splitting, leave it buffered,
                                    # bind the extensions to the conn
                                    conn_tenant = ext["tenant"] or None
                                    conn_trace = ext["trace"]
                                    conn_stale = ext.get("stale", False)
                                    conn_push = ext.get("push", False)
                                    hello = True
                                    break
                        if eof and buf and not hello:
                            # trailing request without a newline is still
                            # answered (readline()-at-EOF parity, pinned by
                            # the native plane's protocol tests)
                            lines.append(buf.decode("utf-8"))
                            buf.clear()
                        if not lines:
                            return
                        if len(lines) > 1:
                            # only multi-line bursts are recorded: a
                            # single-line burst is the complement
                            # (requests_total minus the histogram count)
                            # and observing the constant 1 per request is
                            # measurable on a ~0.1 ms round trip
                            outer._obs_burst.observe(len(lines))
                        # submit ALL, then resolve in order
                        replies = [
                            outer._dispatch_async(ln, burst=len(lines),
                                                  push_sink=sink)
                            for ln in lines
                        ]
                        if len(lines) > 1:
                            # the burst is fully submitted: let the
                            # dispatcher fire without waiting out the
                            # coalescing window for arrivals that were
                            # never coming
                            outer._flush_batchers()
                        out = b"".join(
                            (r.resolve() if isinstance(r, _DeferredReply)
                             else r).encode("utf-8") + b"\n"
                            for r in replies
                        )
                        try:
                            sink.write_reply(out)
                        except (BrokenPipeError, OSError):
                            return
                        if hello:
                            outer._serve_binary(sock, self.wfile, buf, eof,
                                                tenant=conn_tenant,
                                                trace=conn_trace,
                                                stale=conn_stale,
                                                push=conn_push)
                            return
                        if eof:
                            return
                finally:
                    outer._drop_push_sink(sink)
                    with outer._conn_lock:
                        outer._conns.discard(self.connection)
                        outer._conn_threads.discard(
                            threading.current_thread())

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _merged_range_index(self, state: str, table) -> tuple:
        """(sorted fid array, aligned weight array, bucket-id set) over
        every parseable bucket row of `state`, rebuilt when the table's
        mutation version moves.  Per-bucket parses ride the payload-keyed
        cache, so a rebuild after a republish only re-parses changed rows.
        Rows whose key is not an int or whose payload is not ``idx:w;...``
        are skipped — on a flat-model table the index is empty and every
        queried bucket reports missing, which is what DOT against an
        un-partitioned state means."""
        ver = getattr(table, "version", None)
        cached = self._dot_merged.get(state)
        if cached is not None and ver is not None and cached[0] == ver:
            return cached[1], cached[2], cached[3]
        # single-flight rebuild: with a stale entry available, serve it
        # rather than pile K handler threads onto K identical O(model)
        # rebuilds after one mutation (same serve-stale design as the
        # top-k index); the FIRST build has nothing to serve, so it blocks
        if not self._dot_build_lock.acquire(blocking=cached is None):
            return cached[1], cached[2], cached[3]
        try:
            return self._rebuild_merged_range_index(state, table)
        finally:
            self._dot_build_lock.release()

    def _rebuild_merged_range_index(self, state: str, table) -> tuple:
        import numpy as np

        ver = getattr(table, "version", None)
        cached = self._dot_merged.get(state)
        if cached is not None and ver is not None and cached[0] == ver:
            return cached[1], cached[2], cached[3]  # built while we waited
        # the per-payload cache must hold every bucket row, or each rebuild
        # re-parses the evicted ones forever (FIFO churn at >cap buckets)
        n_rows = len(table)
        if n_rows * 2 > self._dot_cache.max_entries:
            self._dot_cache.max_entries = n_rows * 2
        rows = []
        for key, payload in table.items():
            try:
                rows.append((int(key), payload))
            except ValueError:
                continue
        # rows concatenate in ASCENDING BUCKET order (table iteration is
        # shard-hash order, the native store's is hash-bucket order —
        # neither is publish order, so cross-row duplicate-fid last-wins
        # must be pinned to something both planes can reproduce)
        rows.sort(key=lambda r: r[0])
        fid_parts, w_parts, buckets = [], [], set()
        for bucket, payload in rows:
            try:
                idx, w = self._dot_cache.lookup(payload)
            except ValueError:
                continue  # not an idx:w;... row (e.g. a flat-model row)
            buckets.add(bucket)
            fid_parts.append(idx)
            w_parts.append(w)
        if fid_parts:
            # cross-bucket duplicate fids resolve last-wins, like in-row
            fids, ws = sort_dedup_last(np.concatenate(fid_parts),
                                       np.concatenate(w_parts))
        else:
            fids = np.zeros(0, np.int64)
            ws = np.zeros(0, np.float64)
        buckets = frozenset(buckets)
        if ver is not None:
            self._dot_merged[state] = (ver, fids, ws, buckets)
        return fids, ws, buckets

    def _dispatch(self, line: str) -> str:
        """Synchronous dispatch (compat surface): resolves any deferred
        top-k reply before returning."""
        reply = self._dispatch_async(line)
        return reply.resolve() if isinstance(reply, _DeferredReply) else reply

    def _flush_batchers(self) -> None:
        """Release every handler's coalescing window (burst submitted)."""
        for handler in self.topk_handlers.values():
            batcher = getattr(handler, "batcher", None)
            if batcher is not None:
                try:
                    batcher.flush()
                except Exception:
                    pass

    def _dispatch_async(self, line: str, burst: int = 1, push_sink=None):
        """-> reply str, or a _DeferredReply for TOPK/TOPKV riding the
        microbatcher (the handler loop submits a whole pipelined burst
        before resolving any, so the burst shares a device dispatch).
        ``burst`` is the number of lines in the read burst this line
        belongs to — burst members must enqueue rather than take the
        batcher's idle inline path, or the burst serializes back into
        singles."""
        return self._dispatch_parts(line.split("\t"), burst,
                                    push_sink=push_sink)

    def _dispatch_parts(self, parts, burst: int = 1, traced: bool = True,
                        tenant: Optional[str] = None,
                        echo_tid: bool = True, stale: bool = False,
                        push_sink=None):
        """Dispatch over already-split fields — the shared core of the tab
        line loop and the B2 frame loop (binary records arrive pre-split,
        and their fields may legally contain tabs, so they must never take
        a join-then-resplit detour).

        Also the observability choke point: pops an optional trailing
        ``tid=`` trace field FIRST (so every verb handler below sees the
        seed protocol's exact field counts — untraced traffic is
        byte-identical in both directions; an un-negotiated binary
        connection passes ``traced=False``, a ``tr=1`` one gets its
        per-record tid surfaced as the same trailing field but with
        ``echo_tid=False`` — B2 replies are never suffixed), times the
        dispatch, feeds the per-verb counter/latency instruments, and
        echoes the tid on the reply.  Deferred top-k replies do all of
        that at resolve time via the post hook, when their true latency
        is known.

        Tenancy + admission happen here too, before any handler work: a
        trailing ``tn=`` field is popped the same way (tab plane only —
        B2 passes the connection's HELLO-bound tenant via ``tenant``),
        and the tenant's token bucket is charged.  Over quota the request
        is answered ``E\\tover quota`` without touching a table or the
        microbatcher — shedding must cost less than serving."""
        self.requests += 1
        tid = obs_tracing.pop_tid(parts) if traced else None
        if tenant is None and traced:
            tenant = admission_ctl.pop_tenant(parts)
        if not stale and traced:
            # tab-plane per-read staleness opt-in; on B2 the HELLO binds
            # it per connection and arrives via the ``stale`` argument
            stale = proto.pop_stale(parts)
        verb = parts[0] if parts and parts[0] else "?"
        if verb == proto.HELLO_VERB:
            # the accept reply is frozen (old and new clients parse it
            # alike): an ``st=1`` HELLO extension binds staleness to the
            # CONNECTION (handler loop), never to the handshake reply
            stale = False
        t0 = time.perf_counter()
        if self.admission is not None and \
                not self.admission.admit(tenant, verb):
            return self._finish(verb, tid, t0, admission_ctl.SHED_REPLY,
                                shed=True, echo=echo_tid, stale=stale)
        if verb == "METRICS" and len(parts) == 1:
            return self._finish(verb, tid, t0, self._metrics_reply(),
                                echo=echo_tid, stale=stale)
        if verb == "PROFILE" and len(parts) == 1:
            # the profiling plane's scrape verb: the process profiler's
            # folded stacks as one P\t<json> line (the METRICS pattern
            # applied to profiles — obs/profiler.py)
            return self._finish(verb, tid, t0, self._profile_reply(),
                                echo=echo_tid, stale=stale)
        # sampler stage attribution rides the span stack (span enter/exit
        # push/pop the stage) — no per-dispatch stage mark here; even a
        # gated push/pop pair costs ~0.7us, past the 3% hot-path bar.
        # Untraced requests fold under the "-" stage by design.
        reply = self._handle(parts, burst, push_sink)
        if isinstance(reply, _DeferredReply):
            reply.post = lambda rendered, resolver: self._finish(
                verb, tid, t0, rendered, resolver, echo=echo_tid,
                stale=stale)
            return reply
        return self._finish(verb, tid, t0, reply, echo=echo_tid,
                            stale=stale)

    def _serve_binary(self, sock, wfile, buf: bytearray, eof: bool,
                      tenant: Optional[str] = None,
                      trace: bool = False, stale: bool = False,
                      push: bool = False) -> None:
        """B2 frame loop, entered after an accepted HELLO (``serve.proto``).

        One request frame in -> one reply frame out, records answered in
        order; a whole frame is submitted to the microbatcher before any
        reply is resolved, so a client batch coalesces into one device
        dispatch exactly like a tab-mode pipelined burst.  Structural
        corruption answers a single-record ``E\\tbad frame: <reason>``
        frame and closes; a partial frame at EOF is dropped silently (the
        tab plane's unterminated-line parity does not apply — a frame is
        atomic or absent).

        ``push`` (the HELLO's ``su=1``) arms the connection for the push
        plane: subscribe verbs get a sink, and engine deltas ride the
        same write gate as replies (single-record ``PUSH`` frames between
        reply frames).  Without it the subscribe verbs answer the generic
        ``E\\tbad request`` and the wire stays byte-identical."""
        sink = _ConnPushSink(wfile, binary=True)
        try:
            while True:
                try:
                    res = proto.decode_request_frame(buf, trace=trace)
                except proto.ProtoError as e:
                    try:
                        wfile.write(proto.error_frame(str(e)))
                    except (BrokenPipeError, OSError):
                        pass
                    return
                if res is None:
                    if eof:
                        return
                    try:
                        chunk = sock.recv(65536)
                    except (ConnectionResetError, OSError):
                        return
                    if not chunk:
                        eof = True
                        continue
                    buf += chunk
                    continue
                records, consumed = res
                del buf[:consumed]
                if len(records) > 1:
                    self._obs_burst.observe(len(records))
                replies = [
                    # tr=1 records surface their tid as the standard
                    # trailing field (decoder contract), so
                    # ``traced=trace`` reuses the tab plane's pop/span
                    # path — but B2 replies are never tid-suffixed (the
                    # client keeps its own request order)
                    self._dispatch_parts(parts, burst=len(records),
                                         traced=trace, tenant=tenant,
                                         echo_tid=False, stale=stale,
                                         push_sink=sink if push else None)
                    for parts in records
                ]
                if len(records) > 1:
                    self._flush_batchers()
                texts = [
                    r.resolve() if isinstance(r, _DeferredReply) else r
                    for r in replies
                ]
                try:
                    sink.write_reply(proto.encode_reply_frame(texts))
                except (BrokenPipeError, OSError):
                    return
        finally:
            self._drop_push_sink(sink)

    def _verb_obs(self, verb: str) -> tuple:
        inst = self._obs_verbs.get(verb)
        if inst is None:
            reg = obs_metrics.get_registry()
            inst = (
                reg.histogram("tpums_server_latency_seconds", verb=verb),
                reg.counter("tpums_server_errors_total", verb=verb),
            )
            self._obs_verbs[verb] = inst
        return inst

    def _finish(self, verb: str, tid: Optional[str], t0: float,
                reply: str, resolver=None, shed: bool = False,
                echo: bool = True, stale: bool = False) -> str:
        """Request epilogue: per-verb metrics, span event + tid echo for
        traced requests.  ``resolver`` (deferred top-k only) may expose a
        ``pending`` with the microbatcher's span fields — queue wait,
        batch size, device seconds — which join the event AND become
        synthesized child spans (``mb_queue_wait``/``mb_device``) under
        the ``server_reply`` span, so one slow traced query shows WHERE
        its time went.

        ``tid`` is the RAW wire value (possibly ``tid/sid`` — the sid is
        the CLIENT's rpc span, which parents this server's span across
        the process boundary); it is echoed verbatim so the client's
        exact-suffix unstamp keeps working.  ``echo=False`` (B2) skips
        the suffix — frames carry no reply-side tid.

        ``shed`` marks an admission reject: it is an E-reply on the wire
        but NOT a server error — it rides its own counter
        (``tpums_admission_shed_total``), so deliberate shedding never
        reads as the fleet failing."""
        dt = time.perf_counter() - t0
        trace_id, psid = obs_tracing.split_tid(tid) if tid is not None \
            else (None, None)
        if obs_metrics.metrics_enabled():
            # ONE locked observation per request: the per-verb request
            # count is the latency histogram's count, and the
            # ``tpums_server_requests_total`` counter series is
            # synthesized from it at snapshot time (synthesize_requests)
            # instead of paying a second lock on every request
            latency, errors = self._verb_obs(verb)
            # the tid rides along so an exemplar (obs/metrics.py) can link
            # this bucket to this trace; None for untraced requests
            latency.observe(dt, tid=trace_id)
            if reply.startswith("E") and not shed:
                errors.inc()
        if tid is not None:
            t_end = time.time()
            sid = obs_tracing.new_span_id()
            fields = {"verb": verb, "job_id": self.job_id,
                      "port": self.port, "lat_s": round(dt, 6),
                      "ok": not reply.startswith("E")}
            if shed:
                fields["shed"] = True
            pending = getattr(resolver, "pending", None)
            if pending is not None:
                for name in ("queue_wait_s", "batch_size", "device_s"):
                    v = getattr(pending, name, None)
                    if v is not None:
                        fields[name] = round(v, 6) if isinstance(v, float) \
                            else v
            obs_tracing.event("server_reply", tid=trace_id, sid=sid,
                              psid=psid, t0=t_end - dt,
                              dur_s=round(dt, 9), **fields)
            if pending is not None:
                # synthesize the microbatch stages as child spans — the
                # batcher records durations, not span ids, so the tree
                # shape is rebuilt here from the request timeline
                qw = getattr(pending, "queue_wait_s", None)
                dev = getattr(pending, "device_s", None)
                if qw is not None:
                    obs_tracing.event(
                        "mb_queue_wait", tid=trace_id,
                        sid=obs_tracing.new_span_id(), psid=sid,
                        t0=t_end - dt, dur_s=round(qw, 9))
                if dev is not None:
                    obs_tracing.event(
                        "mb_device", tid=trace_id,
                        sid=obs_tracing.new_span_id(), psid=sid,
                        t0=t_end - dev, dur_s=round(dev, 9),
                        batch_size=getattr(pending, "batch_size", None))
        if stale:
            # staleness rides BEFORE the tid echo: the client strips its
            # exact tid suffix first, then pops the trailing st field
            reply = (f"{reply}\t{proto.STALE_FIELD}"
                     f"{self._staleness_value():.3f}")
        if tid is not None and echo:
            reply = f"{reply}\t{obs_tracing.TID_FIELD}{tid}"
        return reply

    def _staleness_value(self) -> float:
        """Seconds this server's state trails its home region; 0.0 on the
        home region itself (or when the provider fails — a read that got
        an answer is not staler for the status file being unreadable)."""
        if self.staleness_fn is None:
            return 0.0
        try:
            v = self.staleness_fn()
        except Exception:
            return 0.0
        return 0.0 if v is None else max(float(v), 0.0)

    def _metrics_reply(self) -> str:
        """The METRICS verb: the whole process-wide registry as ONE
        JSON line (the protocol is line-framed; the Prometheus rendering
        of the same snapshot is a client-side transform — obs/scrape.py)."""
        try:
            snap = obs_metrics.synthesize_requests(
                obs_metrics.get_registry().snapshot(
                    meta={"job_id": self.job_id, "port": self.port,
                          "plane": "python"}))
            return "J\t" + obs_metrics.snapshot_to_json_line(snap)
        except Exception as e:
            return f"E\tmetrics failed: {e}"

    def _profile_reply(self) -> str:
        """The PROFILE verb: the process profiler's stage-keyed folded
        stacks as ONE ``P\\t<json>`` line.  Always answers — with the
        profiler off the stacks are empty but the line still parses, so
        fleet scrapes see 'no samples', not an error."""
        try:
            return obs_profiler.profile_reply_line(
                meta={"job_id": self.job_id, "port": self.port,
                      "plane": "python"})
        except Exception as e:
            return f"E\tprofile failed: {e}"

    def _push(self) -> push_plane.PushEngine:
        """The lazily-built push engine (serve/push.py).  First call —
        the first SUBSCRIBE this process ever serves — registers table
        change listeners; see the constructor comment for why that is
        deferred until someone actually subscribes."""
        eng = self._push_engine
        if eng is None:
            with self._push_create_lock:
                eng = self._push_engine
                if eng is None:
                    eng = push_plane.PushEngine(
                        self.tables, self.topk_handlers, scope=self.job_id)
                    self._push_engine = eng
        return eng

    def _drop_push_sink(self, sink) -> None:
        """Connection epilogue: drop every subscription bound to it."""
        if sink is None or not sink.used:
            return
        eng = self._push_engine
        if eng is not None:
            eng.drop_sink(sink)

    def _handle(self, parts, burst: int = 1, push_sink=None):
        """Verb dispatch over already-split fields (tid removed)."""
        if parts[0] == "PING":
            return f"PONG\t{self.job_id}\t{','.join(self.tables)}"
        if parts[0] == proto.HELLO_VERB and \
                proto.parse_hello(parts) is not None:
            # protocol negotiation: the handler loop flips the connection
            # to B2 on the exact accept line (an old server answers
            # E\tbad request here, which clients read as "tab only").
            # Accepted extensions — a tenant binding (``tn=<t>``) and/or
            # per-record tracing (``tr=1``) — were already captured by
            # the handler loop; the reply stays the frozen 2-field accept
            # so old and new clients parse it alike.  A HELLO with any
            # other extra field stays the generic E\tbad request,
            # byte-identical to the native server.
            if parts[1] == "B2":
                return proto.HELLO_REPLY
            return f"E\tunsupported proto: {parts[1]}"
        if parts[0] == "COUNT" and len(parts) == 2:
            # key count of a state — the ops/metrics surface (Flink exposes
            # state sizes the same way) and the ingest barrier multi-process
            # harnesses use instead of reaching into a worker's table
            _, state = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            return f"C\t{len(table)}"
        if parts[0] == "HEALTH" and len(parts) == 2:
            # liveness/readiness in ONE verb: key count, ingest backlog and
            # the replaying-vs-ready flag, so supervisors and load
            # balancers don't have to infer health from COUNT deltas
            _, state = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            import json as _json

            try:
                if self.health_fn is not None:
                    report = dict(self.health_fn())
                    report.setdefault("state", state)
                else:
                    report = {
                        "state": state, "ready": True, "status": "ready",
                        "backlog_bytes": 0,
                    }
                report["keys"] = len(table)
                report.setdefault("job_id", self.job_id)
                # elastic plane: keep the HEALTH payload schema uniform —
                # a non-elastic worker answers the topology fields with
                # null rather than omitting them (client.topology relies
                # on the keys existing)
                report.setdefault("topology_group", None)
                report.setdefault("generation", None)
                report.setdefault("topology_gen", None)
                # pointer to this replica's metrics snapshot: same
                # endpoint, METRICS verb (scrape clients need no extra
                # port discovery)
                report.setdefault(
                    "metrics_uri",
                    f"tpums://{self.host}:{self.port}/METRICS")
                return "H\t" + _json.dumps(report)
            except Exception as e:
                return f"E\thealth failed: {e}"
        if parts[0] == "GET" and len(parts) == 3:
            _, state, key = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            value = table.get(key)
            return "N" if value is None else f"V\t{value}"
        if parts[0] == "MGET" and len(parts) == 3:
            _, state, keys_csv = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            items = []
            for key in keys_csv.split(","):
                value = table.get(key)
                items.append("N" if value is None else f"V{value}")
            return "M\t" + "\t".join(items)
        if parts[0] == "DOT" and len(parts) == 4:
            # server-side sparse dot over range-partitioned rows: ONE round
            # trip for the whole sparse query, resolved against a merged
            # sorted index over every bucket row (version-keyed, so one
            # searchsorted answers the query instead of one numpy gather
            # per bucket) — no payload shipping/parsing on the client
            # (RangePartitionSVMPredict.java:63,80-101 intent)
            _, state, range_s, qpayload = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            try:
                import numpy as np

                range_ = int(range_s)
                if range_ < 1:
                    return "E\trange must be >= 1"
                # light-weight query parse (the payload is our own client's
                # wire format): one split, one numpy text-parse pass; any
                # garbage token raises and returns an E line.  The strict
                # alternating-separator validator in parse_svm_range_payload
                # costs more than the whole MGET verb at 70-nnz queries.
                acc, missing = 0.0, []
                stripped = qpayload.rstrip(";")
                if stripped:
                    toks = stripped.replace(":", ";").split(";")
                    # structural check (native-plane parity): exactly one
                    # colon per segment and no empty interior segments —
                    # an even token count alone would accept "1:2:3:4"
                    n_pairs = len(toks) // 2
                    if (len(toks) % 2
                            or stripped.count(":") != n_pairs
                            or stripped.count(";") != n_pairs - 1):
                        raise ValueError(f"malformed pair in {stripped[:40]!r}")
                    flat = np.array(toks)
                    qf = flat[0::2].astype(np.int64)
                    qv = flat[1::2].astype(np.float64)
                    fids, ws, bucket_set = self._merged_range_index(
                        state, table)
                    got, hit = gather_sorted(fids, ws, qf)
                    acc = float(qv @ got)
                    # a bucket with no model row can only show up among the
                    # missed fids — the common all-hit query skips this
                    missed = qf[~hit]
                    if missed.size:
                        missing = [int(b) for b in
                                   np.unique(missed // range_).tolist()
                                   if int(b) not in bucket_set]
            except Exception as e:
                return f"E\tdot failed: {e}"
            return f"D\t{acc!r}\t{','.join(str(b) for b in missing)}"
        if parts[0] in ("TOPK", "TOPKV") and len(parts) == 4:
            # TOPK resolves the user's factors server-side; TOPKV scores an
            # explicit query vector (operands: state, k, payload)
            if parts[0] == "TOPK":
                _, state, query_arg, k_s = parts
            else:
                _, state, k_s, query_arg = parts
            handler = self.topk_handlers.get(state)
            if handler is None or (
                parts[0] == "TOPKV" and not hasattr(handler, "by_vector")
            ):
                return f"E\tno topk index for state: {state}"
            try:
                k = int(k_s)
                if k < 1:
                    return "E\tk must be >= 1"
                submit = getattr(handler, "submit_query", None)
                if submit is not None:
                    # enqueue NOW, render the reply at resolve time: the
                    # caller can submit a whole burst before parking, so
                    # pipelined requests coalesce in the microbatcher
                    return _DeferredReply(
                        submit(parts[0], query_arg, k, burst=burst))
                fn = handler if parts[0] == "TOPK" else handler.by_vector
                payload = fn(query_arg, k)
            except Exception as e:
                return f"E\ttopk failed: {e}"
            return "N" if payload is None else f"V\t{payload}"
        if parts[0] in ("SUBSCRIBE", "RESUME") and \
                len(parts) == (5 if parts[0] == "SUBSCRIBE" else 6):
            # push plane (serve/push.py).  ``push_sink`` is the opt-in
            # gate: on B2 it exists only after a ``su=1`` HELLO; on tab
            # the verb itself is the opt-in, so the sink is always
            # offered.  Without a sink the verbs answer the generic bad
            # request — byte-identical to a server without a push plane.
            if push_sink is None:
                return "E\tbad request"
            _, state, kind, arg, k_s = parts[:5]
            try:
                k = int(k_s)
            except ValueError:
                return "E\tbad request"
            try:
                eng = self._push()
                push_sink.used = True
                if parts[0] == "SUBSCRIBE":
                    sub_id, seq, snapshot = eng.subscribe(
                        state, kind, arg, k, push_sink)
                    return f"S\t{sub_id}\t{seq}\t{snapshot}"
                mode, sub_id, seq, snapshot = eng.resume(
                    state, kind, arg, k, parts[5], push_sink)
                if mode == "replay":
                    return f"R\t{sub_id}\t{seq}"
                return f"S\t{sub_id}\t{seq}\t{snapshot}"
            except push_plane.PushError as e:
                return f"E\t{e}"
            except Exception as e:
                return f"E\tsubscribe failed: {e}"
        if parts[0] == "UNSUB" and len(parts) == 2:
            if push_sink is None:
                return "E\tbad request"
            eng = self._push_engine
            if eng is not None and eng.unsubscribe(parts[1]):
                return f"U\t{parts[1]}"
            return f"E\tunknown subscription: {parts[1]}"
        return "E\tbad request"

    def start(self) -> "LookupServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="lookup-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        # quiesce persistent connections: shutting the sockets unblocks the
        # handlers' readline, then join them so no request is in flight
        # when the caller tears down the backing state (ServingJob.stop()
        # closes the native store right after this returns)
        import socket as _socket

        with self._conn_lock:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        for t in threads:
            t.join(timeout=5)
        # stop the push-delivery thread (after the handler quiesce: a
        # handler mid-SUBSCRIBE must not race the engine teardown)
        if self._push_engine is not None:
            try:
                self._push_engine.close()
            except Exception:
                pass
        # stop the top-k microbatcher dispatchers (drains their queues
        # first, so no late in-flight query parks forever); handlers
        # without a close() — plain callables in tests — are fine as-is
        for h in self.topk_handlers.values():
            close = getattr(h, "close", None)
            if callable(close):
                try:
                    close()
                except Exception:
                    pass
        # the quiesce guarantee must be ENFORCED, not assumed: a handler
        # wedged in _dispatch (e.g. a long device-side TOPK) surviving the
        # join would race the caller's store teardown — make it loud
        wedged = [t.name for t in threads if t.is_alive()]
        if wedged:
            import logging

            logging.getLogger(__name__).error(
                "server stop(): %d handler thread(s) still alive after "
                "quiesce join: %s — backing state teardown may race a live "
                "request", len(wedged), wedged,
            )
