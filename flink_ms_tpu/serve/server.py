"""Lookup server — the serving data plane, counterpart of Flink's Netty
KvState server queried by ``QueryClientHelper.queryState``
(``QueryClientHelper.java:104-139``).

Line protocol over TCP (persistent connections, thread per client):

    request:  ``GET\\t<state_name>\\t<key>\\n``
              ``MGET\\t<state_name>\\t<k1>,<k2>,...\\n``  (batched point gets)
              ``TOPK\\t<state_name>\\t<user_id>\\t<k>\\n``  (device-scored top-k)
              ``TOPKV\\t<state_name>\\t<k>\\t<f1;f2;...>\\n``  (top-k by an
                                  explicit query vector — lets a sharded
                                  client fan out across workers that only
                                  hold a slice of the catalog)
              ``COUNT\\t<state_name>\\n``  (key count — ops/metrics surface
                                  and multi-process ingest barrier)
              ``PING\\n``
    response: ``V\\t<value>\\n``   key found / top-k payload ``item:score;...``
              ``N\\n``            unknown key (client maps to Optional.empty,
                                  mirroring UnknownKeyOrNamespace handling)
              ``M\\t<i1>\\t<i2>...\\n``  MGET reply, one item per key in
                                  request order: ``N`` missing, ``V<value>``
                                  found (values are tab-free by contract —
                                  model rows are CSV/semicolon text)
              ``E\\t<msg>\\n``    error (unknown state name, bad request)
              ``C\\t<n>\\n``      COUNT reply
              ``PONG\\t<job_id>\\t<state_name>\\n``

The batched verb exists to beat the reference's serving hot spot: its online
SGD pays two Netty round trips per rating (SGD.java:172-173) and its MSE job
one per rating plus one per user group (MSE.java:129-158); MGET folds each
of those into a single round trip.

A C++ epoll implementation of the same protocol
(``native/lookup_server.cpp``, wrapped by
``native_store.NativeLookupServer``, enabled with ``--nativeServer true`` on
the rocksdb backend) serves the full verb set straight from the persistent
store, including catalog-scored TOPK/TOPKV (round 4); this Python server is
the default and the semantics contract — the native plane's replies are
byte-parity-tested against it.
"""

from __future__ import annotations

import socketserver
import threading
from typing import Dict, Optional

from .table import ModelTable


class LookupServer:
    def __init__(
        self,
        tables: Dict[str, ModelTable],
        host: str = "0.0.0.0",
        port: int = 6123,
        job_id: str = "local",
        topk_handlers: Optional[Dict[str, object]] = None,
    ):
        self.tables = tables
        self.job_id = job_id
        self.topk_handlers = topk_handlers or {}
        self.requests = 0  # observability; also lets tests assert round trips
        # live persistent connections + their handler threads: clients hold
        # sockets open across many requests, so TCPServer.shutdown() alone
        # leaves handlers serving AFTER stop() returns — the round-3 long
        # soak caught a handler reading the native store after the owning
        # job closed it (tpums I/O failure; a use-after-close)
        self._conns: set = set()
        self._conn_threads: set = set()
        self._conn_lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                with outer._conn_lock:
                    outer._conns.add(self.connection)
                    outer._conn_threads.add(threading.current_thread())
                try:
                    while True:
                        try:
                            line = self.rfile.readline()
                        except (ConnectionResetError, OSError):
                            break
                        if not line:
                            break
                        reply = outer._dispatch(
                            line.decode("utf-8").rstrip("\n"))
                        try:
                            self.wfile.write(reply.encode("utf-8") + b"\n")
                        except (BrokenPipeError, OSError):
                            break
                finally:
                    with outer._conn_lock:
                        outer._conns.discard(self.connection)
                        outer._conn_threads.discard(
                            threading.current_thread())

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, line: str) -> str:
        self.requests += 1
        parts = line.split("\t")
        if parts[0] == "PING":
            return f"PONG\t{self.job_id}\t{','.join(self.tables)}"
        if parts[0] == "COUNT" and len(parts) == 2:
            # key count of a state — the ops/metrics surface (Flink exposes
            # state sizes the same way) and the ingest barrier multi-process
            # harnesses use instead of reaching into a worker's table
            _, state = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            return f"C\t{len(table)}"
        if parts[0] == "GET" and len(parts) == 3:
            _, state, key = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            value = table.get(key)
            return "N" if value is None else f"V\t{value}"
        if parts[0] == "MGET" and len(parts) == 3:
            _, state, keys_csv = parts
            table = self.tables.get(state)
            if table is None:
                return f"E\tunknown state: {state}"
            items = []
            for key in keys_csv.split(","):
                value = table.get(key)
                items.append("N" if value is None else f"V{value}")
            return "M\t" + "\t".join(items)
        if parts[0] in ("TOPK", "TOPKV") and len(parts) == 4:
            # TOPK resolves the user's factors server-side; TOPKV scores an
            # explicit query vector (operands: state, k, payload)
            if parts[0] == "TOPK":
                _, state, query_arg, k_s = parts
            else:
                _, state, k_s, query_arg = parts
            handler = self.topk_handlers.get(state)
            if handler is None or (
                parts[0] == "TOPKV" and not hasattr(handler, "by_vector")
            ):
                return f"E\tno topk index for state: {state}"
            fn = handler if parts[0] == "TOPK" else handler.by_vector
            try:
                k = int(k_s)
                if k < 1:
                    return "E\tk must be >= 1"
                payload = fn(query_arg, k)
            except Exception as e:
                return f"E\ttopk failed: {e}"
            return "N" if payload is None else f"V\t{payload}"
        return "E\tbad request"

    def start(self) -> "LookupServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="lookup-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        # quiesce persistent connections: shutting the sockets unblocks the
        # handlers' readline, then join them so no request is in flight
        # when the caller tears down the backing state (ServingJob.stop()
        # closes the native store right after this returns)
        import socket as _socket

        with self._conn_lock:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        for t in threads:
            t.join(timeout=5)
        # the quiesce guarantee must be ENFORCED, not assumed: a handler
        # wedged in _dispatch (e.g. a long device-side TOPK) surviving the
        # join would race the caller's store teardown — make it loud
        wedged = [t.name for t in threads if t.is_alive()]
        if wedged:
            import logging

            logging.getLogger(__name__).error(
                "server stop(): %d handler thread(s) still alive after "
                "quiesce join: %s — backing state teardown may race a live "
                "request", len(wedged), wedged,
            )
