"""ctypes binding for the C++ persistent KV store (native/store.cpp) — the
``--stateBackend rocksdb`` parity mode (SURVEY.md §2.4: the reference keeps
served state in RocksDB through JNI; here a bitcask-style C++ log-structured
store plays that role, bound through ctypes because pybind11 isn't in the
image).

Build on demand: if ``native/libtpums.so`` is missing, ``make -C native``
is invoked once (g++ is baked into the image).
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import threading
from typing import Iterator, List, Optional, Tuple

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libtpums.so"))
_lib = None
_lib_lock = threading.Lock()

_KEY_CB = ctypes.CFUNCTYPE(
    None, ctypes.POINTER(ctypes.c_char), ctypes.c_uint32, ctypes.c_void_p
)


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # TPUMS_NATIVE_LIB overrides the library path without the rebuild
        # logic — used by the sanitizer gates (tests/test_native_sanitizers)
        # to load the tsan/asan-instrumented builds
        override = os.environ.get("TPUMS_NATIVE_LIB")
        if override:
            _lib = _declare_abi(ctypes.CDLL(override))
            return _lib
        # rebuild when the .so is missing or older than its sources: a stale
        # prebuilt .so under newer declared argtypes would corrupt the ABI
        # silently, while an up-to-date .so must keep loading on machines
        # with no toolchain at all
        native_dir = os.path.abspath(_NATIVE_DIR)
        sources = [
            os.path.join(native_dir, n)
            for n in ("store.cpp", "lookup_server.cpp", "arena.cpp",
                      "tpums.h", "tpums_internal.h", "Makefile")
        ]
        stale = not os.path.exists(_SO_PATH) or any(
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO_PATH)
            for src in sources
        )
        if stale:
            # cross-process build lock: _lib_lock is per-process only, and
            # two concurrent `make` runs would race on the link output
            lock_path = os.path.join(native_dir, ".build.lock")
            with open(lock_path, "w") as lock_f:
                fcntl.flock(lock_f, fcntl.LOCK_EX)
                still_stale = not os.path.exists(_SO_PATH) or any(
                    os.path.exists(src)
                    and os.path.getmtime(src) > os.path.getmtime(_SO_PATH)
                    for src in sources
                )
                if still_stale:
                    proc = subprocess.run(
                        ["make", "-C", native_dir],
                        capture_output=True,
                        text=True,
                    )
                    if proc.returncode != 0:
                        # surface the compiler output, not just the exit status
                        raise RuntimeError(
                            "building native store failed "
                            f"(exit {proc.returncode}):\n"
                            f"{proc.stdout}\n{proc.stderr}"
                        )
        _lib = _declare_abi(ctypes.CDLL(_SO_PATH))
        return _lib


def _declare_abi(lib):
    lib.tpums_open.restype = ctypes.c_void_p
    lib.tpums_open.argtypes = [ctypes.c_char_p]
    lib.tpums_put.restype = ctypes.c_int
    lib.tpums_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.tpums_get.restype = ctypes.POINTER(ctypes.c_char)
    lib.tpums_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int),
    ]
    lib.tpums_free_buf.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.tpums_delete.restype = ctypes.c_int
    lib.tpums_delete.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.tpums_count.restype = ctypes.c_uint64
    lib.tpums_count.argtypes = [ctypes.c_void_p]
    lib.tpums_flush.restype = ctypes.c_int
    lib.tpums_flush.argtypes = [ctypes.c_void_p]
    lib.tpums_keys.restype = ctypes.c_int
    lib.tpums_keys.argtypes = [ctypes.c_void_p, _KEY_CB, ctypes.c_void_p]
    lib.tpums_log_bytes.restype = ctypes.c_uint64
    lib.tpums_log_bytes.argtypes = [ctypes.c_void_p]
    lib.tpums_live_bytes.restype = ctypes.c_uint64
    lib.tpums_live_bytes.argtypes = [ctypes.c_void_p]
    lib.tpums_compact.restype = ctypes.c_int
    lib.tpums_compact.argtypes = [ctypes.c_void_p]
    lib.tpums_close.argtypes = [ctypes.c_void_p]
    lib.tpums_ingest_buf.restype = ctypes.c_int
    lib.tpums_ingest_buf.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.tpums_arena_open.restype = ctypes.c_void_p
    lib.tpums_arena_open.argtypes = [ctypes.c_char_p]
    lib.tpums_arena_refresh.restype = ctypes.c_int
    lib.tpums_arena_refresh.argtypes = [ctypes.c_void_p]
    lib.tpums_arena_read_retries.restype = ctypes.c_uint64
    lib.tpums_arena_read_retries.argtypes = [ctypes.c_void_p]
    lib.tpums_arena_stats.restype = ctypes.c_int
    lib.tpums_arena_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
    ]
    lib.tpums_arena_write_stats.restype = ctypes.c_int
    lib.tpums_arena_write_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    try:
        # profiling plane (PR 19+): absent from older .so builds — every
        # caller treats the missing symbol as "no CPU data", like a
        # sidecar that predates the write_cpu_ns field
        lib.tpums_arena_write_cpu_seconds.restype = ctypes.c_int
        lib.tpums_arena_write_cpu_seconds.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ]
    except AttributeError:
        pass
    lib.tpums_arena_writer_open.restype = ctypes.c_void_p
    lib.tpums_arena_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.tpums_arena_writer_close.argtypes = [ctypes.c_void_p]
    lib.tpums_arena_put_batch.restype = ctypes.c_longlong
    lib.tpums_arena_put_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.tpums_arena_cas_floats.restype = ctypes.c_int
    lib.tpums_arena_cas_floats.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.tpums_server_start.restype = ctypes.c_void_p
    lib.tpums_server_start.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tpums_server_start2.restype = ctypes.c_void_p
    lib.tpums_server_start2.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.tpums_server_start3.restype = ctypes.c_void_p
    lib.tpums_server_start3.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int,
    ]
    lib.tpums_server_set_health.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpums_server_set_trace.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int]
    lib.tpums_server_port.restype = ctypes.c_int
    lib.tpums_server_port.argtypes = [ctypes.c_void_p]
    lib.tpums_server_requests.restype = ctypes.c_uint64
    lib.tpums_server_requests.argtypes = [ctypes.c_void_p]
    lib.tpums_server_io_stats.restype = ctypes.c_int
    lib.tpums_server_io_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.tpums_server_stop.argtypes = [ctypes.c_void_p]
    return lib


class StoreLockedError(OSError):
    """Another process holds the store's writer lock."""


class NativeStore:
    """Persistent string->string store backed by the C++ log."""

    def __init__(self, directory: str):
        self._lib = _load_lib()
        os.makedirs(directory, exist_ok=True)
        self._h = self._lib.tpums_open(directory.encode("utf-8"))
        if not self._h:
            if self._is_locked(directory):
                raise StoreLockedError(
                    f"store {directory} is locked by another writer"
                )
            raise OSError(f"tpums_open failed for {directory}")
        self.directory = directory
        # guards every native call against close(): a thread that captured
        # self._h just before close() frees the Store would otherwise
        # dereference freed memory (TOCTOU caught by the round-3 long soak
        # as a tpums_get I/O failure on a live key).  The native layer
        # already serializes under its own mutex, so this adds no new
        # contention — it only makes close() an exclusion point.
        self._call_lock = threading.RLock()

    def _live_handle(self):
        h = self._h
        if not h:
            raise OSError(f"store {self.directory} is closed")
        return h

    @staticmethod
    def _is_locked(directory: str) -> bool:
        import fcntl

        log = os.path.join(directory, "data.log")
        try:
            fd = os.open(log, os.O_RDWR)
        except OSError:
            return False
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            fcntl.flock(fd, fcntl.LOCK_UN)
            return False
        except OSError:
            return True
        finally:
            os.close(fd)

    def put(self, key: str, value: str) -> None:
        k = key.encode("utf-8")
        v = value.encode("utf-8")
        with self._call_lock:
            if self._lib.tpums_put(self._live_handle(), k, len(k), v,
                                   len(v)) != 0:
                raise OSError("tpums_put failed")

    def ingest_buf(self, data: bytes, mode: int) -> Tuple[int, int]:
        """Bulk-ingest a chunk of complete journal lines natively.

        mode 0 = ALS rows (key ``id-T``), 1 = SVM rows (key = first comma
        token).  -> (rows ingested, parse errors)."""
        rows = ctypes.c_uint64(0)
        errs = ctypes.c_uint64(0)
        with self._call_lock:
            rc = self._lib.tpums_ingest_buf(
                self._live_handle(), data, len(data), mode,
                ctypes.byref(rows), ctypes.byref(errs),
            )
        if rc != 0:
            raise OSError("tpums_ingest_buf failed")
        return int(rows.value), int(errs.value)

    def get(self, key: str) -> Optional[str]:
        k = key.encode("utf-8")
        vlen = ctypes.c_uint32()
        err = ctypes.c_int()
        with self._call_lock:
            p = self._lib.tpums_get(
                self._live_handle(), k, len(k), ctypes.byref(vlen),
                ctypes.byref(err),
            )
        if not p:
            if err.value:
                # the key exists but its value could not be read — an I/O
                # failure must not masquerade as "key not found"
                raise OSError(f"tpums_get I/O failure for key {key!r}")
            return None
        try:
            return ctypes.string_at(p, vlen.value).decode("utf-8")
        finally:
            self._lib.tpums_free_buf(p)

    def delete(self, key: str) -> None:
        k = key.encode("utf-8")
        with self._call_lock:
            self._lib.tpums_delete(self._live_handle(), k, len(k))

    def __len__(self) -> int:
        with self._call_lock:
            return int(self._lib.tpums_count(self._live_handle()))

    def flush(self) -> None:
        with self._call_lock:
            if self._lib.tpums_flush(self._live_handle()) != 0:
                raise OSError("tpums_flush failed")

    def keys(self) -> List[str]:
        """All live keys (keys are small; values stay on disk)."""
        out: List[str] = []

        def cb(kp, klen, _ctx):
            out.append(ctypes.string_at(kp, klen).decode("utf-8"))

        cb_ref = _KEY_CB(cb)
        with self._call_lock:
            if self._lib.tpums_keys(self._live_handle(), cb_ref, None) != 0:
                raise OSError("tpums_keys failed")
        return out

    def items(self) -> Iterator[Tuple[str, str]]:
        """Stream (key, value) pairs: the key set is snapshotted under the
        store lock, values are fetched lazily — a larger-than-RAM store is
        never materialized at once.  Keys deleted mid-iteration are skipped."""
        for k in self.keys():
            v = self.get(k)
            if v is not None:
                yield k, v

    @property
    def log_bytes(self) -> int:
        with self._call_lock:
            return int(self._lib.tpums_log_bytes(self._live_handle()))

    @property
    def live_bytes(self) -> int:
        with self._call_lock:
            return int(self._lib.tpums_live_bytes(self._live_handle()))

    def compact(self) -> None:
        with self._call_lock:
            if self._lib.tpums_compact(self._live_handle()) != 0:
                raise OSError("tpums_compact failed")

    def maybe_compact(self, min_bytes: int = 16 << 20) -> bool:
        if self.log_bytes > min_bytes and self.live_bytes * 2 < self.log_bytes:
            self.compact()
            return True
        return False

    def close(self) -> None:
        with self._call_lock:
            if self._h:
                self._lib.tpums_close(self._h)
                self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeModelTable:
    """ModelTable-compatible surface backed by the persistent store: state
    lives on disk incrementally (RocksDB semantics), so checkpoints are a
    flush + offset marker rather than a full snapshot, and the served model
    can exceed RAM."""

    OFFSET_KEY = "\x01__journal_offset__"

    def __init__(self, store: NativeStore):
        self.store = store
        self._lock = threading.RLock()
        self.puts = 0
        # mutation counter, same contract as ModelTable.version: derived
        # read-side caches (the DOT merged range index) key on it — without
        # it every DOT request would rescan the whole store
        self.version = 0
        self._listeners = []
        self._batch_listeners = []

    def add_change_listener(self, fn, batch_fn=None) -> None:
        """fn(key) on every put (same contract as ModelTable); optional
        ``batch_fn(keys)`` replaces the per-key calls for batched ingest."""
        with self._lock:
            self._listeners.append(fn)
            self._batch_listeners.append(batch_fn)

    def put(self, key: str, value: str) -> None:
        with self._lock:
            self.store.put(key, value)
            self.puts += 1
            self.version += 1
            for fn in self._listeners:
                fn(key)

    def put_many(self, pairs) -> None:
        """Batched ingest (same contract as ModelTable.put_many)."""
        pairs = list(pairs)
        if not pairs:
            return
        self.put_many_columns([k for k, _ in pairs], [v for _, v in pairs])

    def put_many_columns(self, keys, values, hashes=None) -> None:
        """Columnar batched ingest (same contract as
        ``ModelTable.put_many_columns``; ``hashes`` accepted and unused —
        the store hashes internally): one lock acquisition and one
        batched listener notification per chunk.  The store writes stay
        per-row (each is one FFI append), but the listener fan-out no
        longer costs a Python call per key."""
        n = len(keys)
        if n == 0:
            return
        with self._lock:
            store_put = self.store.put
            for key, value in zip(keys, values):
                store_put(key, value)
            self.puts += n
            self.version += 1
            for fn, batch_fn in zip(self._listeners, self._batch_listeners):
                if batch_fn is not None:
                    batch_fn(keys)
                else:
                    for key in keys:
                        fn(key)

    def ingest_lines(self, data: bytes, mode: int) -> Tuple[int, int]:
        """Native bulk ingest of a journal chunk — ONE FFI call instead of
        a Python parse + ctypes put per row.  Only valid when no change
        listeners are registered (the consumer checks and falls back to
        the Python path otherwise, so e.g. top-k dirty tracking keeps
        seeing every key).  -> (rows, parse errors)."""
        with self._lock:
            rows, errs = self.store.ingest_buf(data, mode)
            self.puts += rows
            self.version += 1
            return rows, errs

    def get(self, key: str) -> Optional[str]:
        return self.store.get(key)

    def __len__(self) -> int:
        n = len(self.store)
        return n - (1 if self.store.get(self.OFFSET_KEY) is not None else 0)

    def items(self) -> Iterator[Tuple[str, str]]:
        for k, v in self.store.items():
            if not k.startswith("\x01"):
                yield k, v


class NativeArena:
    """Read-only handle onto a shared-memory factor arena (serve/arena.py)
    written in place by the consumer's mmap.  The handle is interchangeable
    with a NativeStore for every READ verb — ``tpums_get``/``tpums_count``/
    ``tpums_keys_chunk``/... dispatch on the leading handle tag — so
    ``NativeLookupServer(NativeArena(dir), ...)`` serves GET/MGET/B2 and
    builds TOPK/DOT indexes straight from the shared pages with zero
    per-request (or per-row) Python→C++ pushes.  Mutating verbs fail: the
    Python writer owns the pages.
    """

    def __init__(self, directory: str):
        self._lib = _load_lib()
        os.makedirs(directory, exist_ok=True)
        self._h = self._lib.tpums_arena_open(directory.encode("utf-8"))
        if not self._h:
            raise OSError(f"tpums_arena_open failed for {directory}")
        self.directory = directory
        self._call_lock = threading.RLock()

    def _live_handle(self):
        h = self._h
        if not h:
            raise OSError(f"arena {self.directory} is closed")
        return h

    def refresh(self) -> bool:
        """Force a remap check (normally implicit per read).  False while
        no generation file exists yet (writer not started)."""
        with self._call_lock:
            return self._lib.tpums_arena_refresh(self._live_handle()) == 0

    def get(self, key: str) -> Optional[str]:
        k = key.encode("utf-8")
        vlen = ctypes.c_uint32()
        err = ctypes.c_int()
        with self._call_lock:
            p = self._lib.tpums_get(
                self._live_handle(), k, len(k), ctypes.byref(vlen),
                ctypes.byref(err),
            )
        if not p:
            return None  # torn/odd slots read as missing, never as an error
        try:
            return ctypes.string_at(p, vlen.value).decode("utf-8")
        finally:
            self._lib.tpums_free_buf(p)

    def __len__(self) -> int:
        with self._call_lock:
            return int(self._lib.tpums_count(self._live_handle()))

    @property
    def read_retries(self) -> int:
        """Cumulative seqlock read retries (torn/odd slots observed)."""
        with self._call_lock:
            return int(
                self._lib.tpums_arena_read_retries(self._live_handle()))

    def stats(self) -> dict:
        """Gauge snapshot: rows / capacity / resident_bytes / retries /
        load_factor (all 0 while the writer has not created the arena)."""
        vals = [ctypes.c_double(0.0) for _ in range(5)]
        with self._call_lock:
            rc = self._lib.tpums_arena_stats(
                self._live_handle(), *[ctypes.byref(v) for v in vals])
        if rc != 0:
            raise OSError("tpums_arena_stats failed (not an arena handle?)")
        names = ("rows", "capacity", "resident_bytes", "retries",
                 "load_factor")
        return {n: v.value for n, v in zip(names, vals)}

    def write_stats(self) -> Optional[dict]:
        """Write-plane counters from the ``writer.stats`` sidecar the native
        batch writer maintains (batch rows/seconds, CAS outcomes), or None
        while no native writer has ever run against this arena."""
        vals = [ctypes.c_double(0.0) for _ in range(4)]
        with self._call_lock:
            rc = self._lib.tpums_arena_write_stats(
                self._live_handle(), *[ctypes.byref(v) for v in vals])
        if rc != 0:
            return None
        names = ("batch_rows", "batch_seconds", "cas_success", "cas_retry")
        return {n: v.value for n, v in zip(names, vals)}

    def write_cpu_seconds(self) -> Optional[float]:
        """Thread-CPU seconds the native write plane burned (sidecar
        write_cpu_ns) — the fleet profile's ``native;arena_writer`` row;
        None while no native writer has run or the .so predates the
        export."""
        fn = getattr(self._lib, "tpums_arena_write_cpu_seconds", None)
        if fn is None:
            return None
        val = ctypes.c_double(0.0)
        with self._call_lock:
            rc = fn(self._live_handle(), ctypes.byref(val))
        return val.value if rc == 0 else None

    def close(self) -> None:
        with self._call_lock:
            if self._h:
                self._lib.tpums_close(self._h)
                self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeLookupServer:
    """C++ epoll lookup server (native/lookup_server.cpp) serving point GETs
    straight from an open NativeStore — the Netty-KvState-parity data plane
    with no Python on the hot path.  Speaks the full verb surface of
    ``serve.server.LookupServer`` (tab protocol plus the HELLO-negotiated
    B2 binary frames of ``serve.proto``).  ``topk_suffixes=(item, user)``
    (e.g. ``("-I", "-U")`` for ALS planes) enables catalog-scored
    TOPK/TOPKV in the C++ server; left None, those verbs answer E like a
    Python server with no registered handler.  HEALTH/METRICS are always
    served: the C++ plane keeps per-verb request/latency/error counters on
    the shared ``obs.metrics.LATENCY_BUCKETS_S`` ladder, so the fleet
    scrape merges native and Python snapshots with identical bounds.
    """

    def __init__(self, store: NativeStore, state_name: str,
                 job_id: str = "local", host: str = "0.0.0.0", port: int = 0,
                 topk_suffixes: Optional[Tuple[str, str]] = None):
        from ..obs import metrics as obs_metrics

        self._lib = store._lib
        self._store = store  # keep the store alive while the server reads it
        item_suf, user_suf = topk_suffixes or (None, None)
        bounds = list(obs_metrics.LATENCY_BUCKETS_S)
        # the ladder crosses the FFI as exact doubles (never re-derived in
        # C++), so merge_snapshots' bounds equality check holds by identity
        bounds_arr = (ctypes.c_double * len(bounds))(*bounds)
        self._h = self._lib.tpums_server_start3(
            store._h,
            state_name.encode("utf-8"),
            job_id.encode("utf-8"),
            host.encode("utf-8"),
            port,
            item_suf.encode("utf-8") if item_suf else None,
            user_suf.encode("utf-8") if user_suf else None,
            bounds_arr,
            len(bounds),
        )
        if not self._h:
            raise OSError(
                f"tpums_server_start failed on {host}:{port} (port in use?)"
            )
        self.state_name = state_name
        self.job_id = job_id
        self.port = int(self._lib.tpums_server_port(self._h))
        # tail-forensics span spill: when TPUMS_TRACE is a file path (the
        # Python plane's event sink, obs/tracing.py), traced requests on
        # this server append their server_reply span records to the SAME
        # file — one fleet-wide spill for obs.forensics to collect
        tpath = os.environ.get("TPUMS_TRACE", "").strip()
        if tpath not in ("", "0", "1", "-"):
            self.set_trace(tpath)

    def set_trace(self, path: Optional[str],
                  max_bytes: Optional[int] = None,
                  keep: Optional[int] = None) -> None:
        """Point the C++ span spill at ``path`` (None/"" disables it).
        ``max_bytes``/``keep`` default to the TPUMS_TRACE_MAX_BYTES /
        TPUMS_TRACE_KEEP rotation knobs, matching the Python sink."""
        if not self._h:
            return

        def _env_int(name: str, default: int) -> int:
            try:
                return int(os.environ.get(name, "") or default)
            except ValueError:
                return default

        if max_bytes is None:
            max_bytes = _env_int("TPUMS_TRACE_MAX_BYTES", 0)  # 0 = C default
        if keep is None:
            keep = _env_int("TPUMS_TRACE_KEEP", -1)  # -1 = C default
        self._lib.tpums_server_set_trace(
            self._h, path.encode("utf-8") if path else None,
            max_bytes, keep)

    def set_health(self, health_json: Optional[str]) -> None:
        """Push the owning job's health dict (one-line JSON) into the C++
        HEALTH verb; the server splices in the live key count and
        metrics_uri.  ``None`` reverts to the synthesized always-ready
        report."""
        if self._h:
            self._lib.tpums_server_set_health(
                self._h,
                health_json.encode("utf-8") if health_json else None,
            )

    @property
    def requests(self) -> int:
        return int(self._lib.tpums_server_requests(self._h)) if self._h else 0

    def io_stats(self) -> dict:
        """Reply-path syscall accounting for the batched socket loop:
        ``recv_calls`` / ``reply_syscalls`` / ``reply_bytes`` cumulative
        counters plus ``uring`` (whether the io_uring backend passed its
        runtime probe).  The syscalls-per-frame tests read deltas from here
        instead of strace."""
        if not self._h:
            return {"recv_calls": 0, "reply_syscalls": 0, "reply_bytes": 0,
                    "uring": False}
        recv = ctypes.c_uint64(0)
        reply = ctypes.c_uint64(0)
        rbytes = ctypes.c_uint64(0)
        uring = ctypes.c_int(0)
        self._lib.tpums_server_io_stats(
            self._h, ctypes.byref(recv), ctypes.byref(reply),
            ctypes.byref(rbytes), ctypes.byref(uring))
        return {"recv_calls": int(recv.value),
                "reply_syscalls": int(reply.value),
                "reply_bytes": int(rbytes.value),
                "uring": bool(uring.value)}

    def start(self) -> "NativeLookupServer":
        return self  # started in __init__; method mirrors LookupServer's API

    def stop(self) -> None:
        if self._h:
            self._lib.tpums_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class NativeStateBackend:
    """State backend for ServingJob: the table IS the durable store.

    ``snapshot`` = fsync + journal-offset marker (cheap, incremental);
    ``restore`` = reopen + read marker; compaction happens opportunistically
    at checkpoint time.
    """

    kind = "rocksdb"

    def __init__(self, checkpoint_uri: str):
        self.store = NativeStore(checkpoint_uri)

    def make_table(self, n_shards: int = 8) -> NativeModelTable:
        del n_shards  # single log; key routing is the hash index itself
        return NativeModelTable(self.store)

    def snapshot(self, table, offset: int) -> None:
        self.store.put(NativeModelTable.OFFSET_KEY, str(offset))
        self.store.flush()
        self.store.maybe_compact()

    def restore(self, table) -> Optional[int]:
        payload = self.store.get(NativeModelTable.OFFSET_KEY)
        return int(payload) if payload is not None else None

    def close(self) -> None:
        self.store.close()
