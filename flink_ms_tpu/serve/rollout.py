"""Blue/green model rollout: versioned model serving on topology generations.

The reference treats a trained model as a deployment artifact: stop the
old ``als-ms`` job, start a new one over the new model transport topic
(PAPER.md §0) — a window where queries fail.  This controller generalizes
the elastic plane's topology-generation machinery (serve/elastic.py) from
*reshaping* a serving group to *replacing the model it serves*:

1. a newly trained model (its own journal dir + topic) is bulk-loaded as
   generation g+1 of the SAME serving group — snapshot-first bootstrap
   (serve/snapshot.py) keeps the warm-up O(state);
2. the warming generation must pass a verification gate behind the ready
   barrier: row count, plus an optional held-out MSE probe (eval/mse.py)
   queried directly against the warming workers BEFORE they can win;
3. CAS publish (``registry.publish_topology`` with the generation's model
   binding attached), drain, GC — the ``ScaleController`` cutover
   protocol verbatim, so in-flight traffic sees zero failed queries;
4. the superseded generation's model binding follows it into the
   topology record's bounded history, and its journal + snapshots are
   retained — ``rollback()`` is one command that rolls *forward* to a new
   generation serving the PREVIOUS model (snapshot-fast, same zero-error
   cutover), rather than a fragile resurrection of stopped processes.

Tenancy: the group name is tenant-qualified (``registry.qualify_group``),
so ``acme``'s ALS rollout and ``globex``'s SVM rollout share one registry
with disjoint records, leases, snapshot scopes and GC.

CLI (one command per op)::

    python -m flink_ms_tpu.serve.rollout --group als \\
        --journalDir /data/v2 --topic models --modelId v2 \\
        --verifyMinRows 1000 [--probeRatings heldout.csv --probeMaxMse 1.2]
    python -m flink_ms_tpu.serve.rollout --group als --rollback
    python -m flink_ms_tpu.serve.rollout --group als --status

Workers spawned by the CLI outlive it (they serve and heartbeat on their
own); what ends with the controller process is respawn supervision and
ownership of older generations.  A resident controller (tests, the chaos
harness, an operator daemon) retains both.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional, Sequence

from . import registry
from .client import RetryPolicy
from .elastic import ScaleController, ScaleError, generation_group
from .ha import HAShardedClient, ReplicaSupervisor


class RolloutError(RuntimeError):
    """A rollout/rollback could not proceed (no model binding, etc.)."""


class VerificationError(RolloutError):
    """The warming generation failed its pre-publish verification gate;
    the cutover was aborted and the active generation kept serving."""


def _parse_factors(payload: Optional[str]):
    """Serving payload ``"f1;f2;..."`` -> list of floats (None passes
    through: a missing key is the caller's skip signal)."""
    if payload is None:
        return None
    return [float(t) for t in payload.split(";") if t]


class RolloutController(ScaleController):
    """``ScaleController`` whose generations differ by WHAT they serve.

    Inherits the whole cutover protocol — lease, warming spawn, all-ready
    barrier, CAS publish, drain, generation GC — and specializes the two
    hooks: ``_verify_generation`` gates the warming MODEL (row count +
    optional MSE probe) and ``_publish_topology`` binds the model to the
    published generation so history knows what every generation served."""

    _EVENT_PREFIX = "rollout"

    def __init__(
        self,
        group: str,
        port_dir: Optional[str] = None,
        *,
        tenant: Optional[str] = None,
        state: str = "ALS_MODEL",
        journal_dir: Optional[str] = None,
        topic: Optional[str] = None,
        **kw,
    ):
        group = registry.qualify_group(group, tenant)
        # default journal binding: whatever model the group currently
        # serves (a fresh controller process attaching to a live group)
        topo = registry.resolve_topology(group)
        model = (topo or {}).get("model") or {}
        super().__init__(
            group,
            journal_dir if journal_dir is not None
            else model.get("journal_dir"),
            topic if topic is not None else model.get("topic"),
            port_dir=port_dir, **kw,
        )
        self.state = state
        self._pending_model: Optional[dict] = None

    # -- protocol hooks ----------------------------------------------------

    def _warming_client(self, gen: int,
                        sup: ReplicaSupervisor) -> HAShardedClient:
        return HAShardedClient(
            sup.num_workers, job_group=generation_group(self.group, gen),
            timeout_s=10.0,
            retry=RetryPolicy(attempts=4, backoff_s=0.05,
                              max_backoff_s=0.5))

    def _verify_generation(self, gen: int,
                           sup: ReplicaSupervisor) -> None:
        """The ready gate's second half: the warming generation answered
        ready (journal caught up), now prove it serves a sane MODEL.
        Queries go straight at the warming workers' shard groups — the
        published topology still points at the old generation, so probing
        is invisible to live traffic."""
        spec = self._pending_model
        if spec is None:
            return  # plain reshape through the inherited scale_to
        min_rows = int(spec.get("verify_min_rows") or 0)
        probe = spec.get("probe")
        if min_rows <= 0 and not probe:
            return
        client = self._warming_client(gen, sup)
        try:
            if min_rows > 0:
                rows = client.total_count(self.state)
                if rows < min_rows:
                    raise VerificationError(
                        f"warming generation {gen} of {self.group!r} "
                        f"holds {rows} rows < required {min_rows} — "
                        f"model {spec.get('model_id')!r} refused")
                self._event("verified", gen=gen, rows=rows)
            if probe:
                self._run_probe(client, gen, probe)
        finally:
            client.close()

    def _run_probe(self, client: HAShardedClient, gen: int,
                   probe: dict) -> None:
        """Held-out MSE gate: score ``probe``'s ratings against the
        warming model via eval/mse.py's reference skip semantics."""
        from ..eval.mse import compute_mse

        max_mse = float(probe["max_mse"])

        def lookup(key: str):
            return _parse_factors(client.query_state(self.state, key))

        def lookup_many(keys: Sequence[str]):
            return [_parse_factors(p)
                    for p in client.query_states(self.state, list(keys))]

        mse, n_scored, n_skipped = compute_mse(
            probe["users"], probe["items"], probe["ratings"],
            lookup, lookup_many=lookup_many)
        if mse is None or n_scored == 0:
            raise VerificationError(
                f"MSE probe scored 0 of {len(probe['ratings'])} held-out "
                f"ratings against warming generation {gen} — "
                "model refused")
        if mse > max_mse:
            raise VerificationError(
                f"warming generation {gen} MSE {mse:.4f} > gate "
                f"{max_mse:.4f} over {n_scored} held-out ratings "
                f"({n_skipped} skipped) — model refused")
        self._event("verified", gen=gen, mse=round(float(mse), 6),
                    scored=n_scored)

    def _publish_topology(self, shards: int, replicas: int, *,
                          expect_gen: int) -> dict:
        extra = None
        if self._pending_model is not None:
            extra = {"model": {
                k: self._pending_model[k]
                for k in ("journal_dir", "topic", "model_id",
                          "rolled_out_at")
                if k in self._pending_model
            }}
        return registry.publish_topology(
            self.group, shards, replicas, expect_gen=expect_gen,
            extra=extra)

    # -- the one-command surface -------------------------------------------

    def rollout(
        self,
        journal_dir: str,
        topic: str,
        *,
        model_id: Optional[str] = None,
        shards: Optional[int] = None,
        replicas: Optional[int] = None,
        verify_min_rows: int = 0,
        probe: Optional[dict] = None,
    ) -> dict:
        """Blue/green replace the group's model -> the published record.

        Spawns generation g+1 bound to ``(journal_dir, topic)``, waits
        for it to bulk-load (snapshot-first) and pass verification
        (``verify_min_rows`` row floor; ``probe`` = ``{"users", "items",
        "ratings", "max_mse"}`` held-out MSE gate), then CAS-cuts over
        and drains g.  Shape defaults to the active topology's (a model
        swap, not a reshape).  On ANY failure the active generation keeps
        serving and the warming one is torn down."""
        topo = self.current()
        if shards is None:
            shards = int(topo["shards"]) if topo else 1
        if replicas is None:
            replicas = (int(topo["replicas"]) if topo
                        else self.replication)
        journal_dir = os.path.abspath(journal_dir)
        self._pending_model = {
            "journal_dir": journal_dir, "topic": topic,
            "model_id": model_id or topic,
            "rolled_out_at": time.time(),
            "verify_min_rows": int(verify_min_rows),
            "probe": probe,
        }
        prev_binding = (self.journal_dir, self.topic)
        # the inherited _spawn_generation reads self.journal_dir/topic —
        # rebinding them IS how generation g+1 gets the new model
        self.journal_dir, self.topic = journal_dir, topic
        try:
            return self.scale_to(shards, replicas, force=True)
        except Exception:
            self.journal_dir, self.topic = prev_binding
            raise
        finally:
            self._pending_model = None

    def rollback(self, *, verify_min_rows: int = 0) -> dict:
        """One-command rollback: re-serve the PREVIOUS model.

        Reads the newest history entry whose model binding differs from
        the active one and rolls it out as a fresh generation — same
        zero-failed-queries cutover, snapshot-fast because the previous
        model's snapshot family was retained under its own journal dir."""
        topo = self.current()
        if topo is None:
            raise RolloutError(
                f"group {self.group!r} has no topology to roll back")
        cur = (topo.get("model") or {})
        cur_key = (cur.get("journal_dir"), cur.get("topic"))
        for h in reversed(list(topo.get("history", ()))):
            m = h.get("model")
            if m and (m.get("journal_dir"), m.get("topic")) != cur_key:
                self._event("rollback", from_gen=int(topo["gen"]),
                            to_model=m.get("model_id"))
                return self.rollout(
                    m["journal_dir"], m["topic"],
                    model_id=m.get("model_id"),
                    shards=int(h.get("shards", topo["shards"])),
                    replicas=int(h.get("replicas", topo["replicas"])),
                    verify_min_rows=verify_min_rows,
                )
        raise RolloutError(
            f"group {self.group!r}: no previous model in the topology "
            "history to roll back to")

    def status(self) -> dict:
        """The active record plus the rollback candidate, for operators."""
        topo = self.current() or {}
        cur = topo.get("model") or {}
        prev = None
        cur_key = (cur.get("journal_dir"), cur.get("topic"))
        for h in reversed(list(topo.get("history", ()))):
            m = h.get("model")
            if m and (m.get("journal_dir"), m.get("topic")) != cur_key:
                prev = m
                break
        return {"group": self.group, "topology": topo or None,
                "model": cur or None, "rollback_to": prev}


def main(argv=None) -> int:
    from ..core.formats import read_ratings
    from ..core.params import Params

    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    if not params.has("group"):
        print(__doc__)
        return 2
    ctl = RolloutController(
        params.get_required("group"),
        port_dir=params.get("portDir", None),
        tenant=params.get("tenant", None),
        state=params.get("state", "ALS_MODEL"),
        state_backend=params.get("stateBackend", "memory"),
        replication=params.get_int("replication", 1),
        ready_timeout_s=float(params.get("readyTimeoutS", "180")),
        snapshots=(params.get_bool("snapshots", True) or None),
    )
    if params.has("status"):
        print(json.dumps(ctl.status(), indent=1, default=str))
        return 0
    try:
        if params.has("rollback"):
            record = ctl.rollback(
                verify_min_rows=params.get_int("verifyMinRows", 0))
        else:
            probe = None
            if params.has("probeRatings"):
                users, items, ratings = read_ratings(
                    params.get_required("probeRatings"),
                    field_delimiter=params.get("fieldDelimiter", "\t"),
                    ignore_first_line=params.get_bool("ignoreFirstLine",
                                                      True))
                probe = {"users": users, "items": items,
                         "ratings": ratings,
                         "max_mse": float(
                             params.get("probeMaxMse", "1e9"))}
            record = ctl.rollout(
                params.get_required("journalDir"),
                params.get("topic", "models"),
                model_id=params.get("modelId", None),
                shards=(params.get_int("shards", 0) or None),
                replicas=(params.get_int("replication", 0) or None),
                verify_min_rows=params.get_int("verifyMinRows", 0),
                probe=probe,
            )
    except (RolloutError, ScaleError, registry.TopologyConflict) as e:
        print(f"rollout failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps({"group": ctl.group, "gen": record["gen"],
                      "shards": record["shards"],
                      "replicas": record["replicas"],
                      "model": record.get("model")}, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
