"""Last-writer-wins journal compaction — the Kafka compacted-topic
property the reference's model transport relies on (PAPER.md §L4), grown
onto the segmented journal.

The journal itself is format-agnostic, so key semantics live here: a fold
pass reads every SEALED segment, keeps only the LAST row per key (plus
every malformed row verbatim, so the consumer's skip-and-count parity is
preserved exactly), and hands the folded bytes back to
``Journal.compact_prefix`` for the atomic segment swap.  Replaying
(compacted prefix + tail) is state-identical to replaying the full
history: within the fold every key carries its newest in-prefix value,
and the untouched tail re-applies anything newer in journal order.

Key extraction mirrors the chunk parser / per-row parsers byte-for-byte
(``core.formats.split_journal_chunk``, ``serve.consumer.parse_*_record``;
the compaction fuzz test pins the parity):

- ALS rows need >= 2 commas; key is ``"<id>-<T>"`` (first comma -> "-",
  key ends at the second comma).  Fewer commas = malformed -> kept.
- SVM rows split at the FIRST comma; a comma-less row IS its own key
  (``str.partition`` semantics) and is never malformed.

Knobs (all ``TPUMS_COMPACT_*``):

- ``TPUMS_COMPACT``            enable the background compactor in serving
                               workers ("1"; default off)
- ``TPUMS_COMPACT_INTERVAL_S`` background fold cadence (default 30)
- ``TPUMS_COMPACT_MIN_SEGMENTS`` minimum sealed segments before a fold
                               pass bothers (default 2)

One compactor per journal directory: the fold/swap is crash-safe against
readers and the producer (atomic rename + shadowing), but two concurrent
compactors would duplicate work — the serving CLI only enables the
background thread on worker 0 / replica 0 of a fleet, and an elastic
worker additionally stands down (``active_fn``) unless its topology
generation is the group's ACTIVE one, so a warming gen-g+1 fleet never
folds the shared journal alongside the still-active gen g.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core.formats import CHUNK_ALS, CHUNK_SVM
from .journal import Journal


def compact_interval_s() -> float:
    try:
        return max(
            float(os.environ.get("TPUMS_COMPACT_INTERVAL_S", 30.0)), 0.05
        )
    except ValueError:
        return 30.0


def compact_min_segments() -> int:
    try:
        return max(int(os.environ.get("TPUMS_COMPACT_MIN_SEGMENTS", 2)), 1)
    except ValueError:
        return 2


def compact_enabled() -> bool:
    return os.environ.get("TPUMS_COMPACT", "0") == "1"


# -- key extraction ----------------------------------------------------------

def als_key(line: str) -> Optional[str]:
    """``id,T,payload`` -> ``"id-T"``; None (malformed) below 2 commas."""
    i = line.find(",")
    if i < 0:
        return None
    jj = line.find(",", i + 1)
    if jj < 0:
        return None
    return f"{line[:i]}-{line[i + 1:jj]}"


def svm_key(line: str) -> Optional[str]:
    """``key,payload`` -> raw first token; a comma-less row is its own key
    (str.partition never fails a row)."""
    i = line.find(",")
    return line if i < 0 else line[:i]


_MODE_KEY_FNS: Dict[int, Callable[[str], Optional[str]]] = {
    CHUNK_ALS: als_key,
    CHUNK_SVM: svm_key,
}


def key_fn_for(parse_fn) -> Callable[[str], Optional[str]]:
    """Derive the per-line key extractor from a consumer parse function.

    Standard parsers advertise ``columnar_mode`` (including the sharded
    wrapper, which must NOT be called directly here — its ownership filter
    returns None for rows other workers own, and compaction folds the
    SHARED journal for everyone).  Custom parsers fall back to calling
    ``parse_fn`` per line, treating a ValueError as malformed."""
    mode = getattr(parse_fn, "columnar_mode", None)
    if mode in _MODE_KEY_FNS:
        return _MODE_KEY_FNS[mode]

    def _kf(line: str) -> Optional[str]:
        try:
            parsed = parse_fn(line)
        except ValueError:
            return None
        return None if parsed is None else parsed[0]

    return _kf


# -- the fold ----------------------------------------------------------------

def fold_chunk(
    data: bytes, key_fn: Callable[[str], Optional[str]]
) -> Tuple[bytes, dict]:
    """Fold complete journal rows last-writer-wins per key.

    Keeps: the LAST occurrence of every key (in the position of that last
    occurrence, so per-key order is untouched) and every malformed row
    verbatim (the consumer skips-and-counts them; dropping any would break
    parse-error parity between compacted and full replay).  Empty lines
    are dropped — both ingest paths skip them silently."""
    text = data.decode("utf-8")
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    last: Dict[str, int] = {}
    keys: List[Optional[str]] = []
    for idx, line in enumerate(lines):
        stripped = line[:-1] if line.endswith("\r") else line
        if not stripped:
            keys.append(None)
            continue
        k = key_fn(stripped)
        keys.append(k)
        if k is not None:
            last[k] = idx
    kept: List[str] = []
    rows_in = 0
    malformed = 0
    for idx, line in enumerate(lines):
        stripped = line[:-1] if line.endswith("\r") else line
        if not stripped:
            continue  # empty line: state- and count-neutral
        rows_in += 1
        k = keys[idx]
        if k is None:
            malformed += 1
            kept.append(line)
        elif last[k] == idx:
            kept.append(line)
    out = ("\n".join(kept) + "\n").encode("utf-8") if kept else b""
    return out, {
        "rows_in": rows_in,
        "rows_out": len(kept),
        "rows_folded": rows_in - len(kept),
        "malformed_kept": malformed,
        "distinct_keys": len(last),
    }


def compact_journal(
    journal: Journal,
    parse_fn=None,
    key_fn: Optional[Callable[[str], Optional[str]]] = None,
    min_segments: Optional[int] = None,
) -> Optional[dict]:
    """One fold pass over the journal's sealed prefix.  Returns merged
    journal+fold stats, or None when there was nothing to fold."""
    if key_fn is None:
        if parse_fn is None:
            raise ValueError("compact_journal needs parse_fn or key_fn")
        key_fn = key_fn_for(parse_fn)
    if min_segments is None:
        min_segments = compact_min_segments()
    fold_stats: dict = {}

    def _fold(data: bytes) -> bytes:
        out, st = fold_chunk(data, key_fn)
        fold_stats.update(st)
        return out

    stats = journal.compact_prefix(_fold, min_segments=min_segments)
    if stats is None:
        return None
    stats.update(fold_stats)
    return stats


class CompactorThread(threading.Thread):
    """Background fold pass on a fixed cadence, stopping with its owner.

    Failures never propagate — a fold pass that loses a race (retention,
    a concurrent fold, the producer rotating) simply retries next tick.
    ``active_fn`` (checked fresh each tick) lets the owner stand the
    compactor down without stopping it — an elastic worker passes its
    am-I-the-active-generation check so exactly one fleet folds the
    shared journal through a cutover."""

    def __init__(
        self,
        journal: Journal,
        parse_fn,
        interval_s: Optional[float] = None,
        min_segments: Optional[int] = None,
        stop_event: Optional[threading.Event] = None,
        active_fn: Optional[Callable[[], bool]] = None,
    ):
        super().__init__(name="journal-compactor", daemon=True)
        self.journal = journal
        self.key_fn = key_fn_for(parse_fn)
        self.interval_s = (
            compact_interval_s() if interval_s is None else interval_s
        )
        self.min_segments = (
            compact_min_segments() if min_segments is None else min_segments
        )
        # NOT self._stop: that would shadow threading.Thread's private
        # _stop() method and blow up inside Thread.join()
        self._stop_event = (
            stop_event if stop_event is not None else threading.Event()
        )
        self.active_fn = active_fn
        self.passes = 0
        self.folds = 0
        self.rows_folded = 0
        self.bytes_reclaimed = 0
        self.standdowns = 0
        self.last_stats: Optional[dict] = None
        self.last_error: Optional[str] = None

    def stop(self) -> None:
        self._stop_event.set()

    def run_once(self) -> Optional[dict]:
        self.passes += 1
        try:
            stats = compact_journal(
                self.journal, key_fn=self.key_fn,
                min_segments=self.min_segments,
            )
        except Exception as e:  # never kill the owner over a fold pass
            self.last_error = str(e)
            print(f"[compact] fold pass failed: {e}", file=sys.stderr)
            return None
        if stats is not None:
            self.folds += 1
            self.rows_folded += stats.get("rows_folded", 0)
            self.bytes_reclaimed += stats.get("bytes_reclaimed", 0)
            self.last_stats = stats
        return stats

    def run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            if self.active_fn is not None and not self.active_fn():
                # e.g. a warming elastic generation: the gen-g fleet is
                # still the journal's compactor — skip, re-check next tick
                self.standdowns += 1
                continue
            self.run_once()


def _main(argv=None) -> int:
    """``python -m flink_ms_tpu.serve.compact --journalDir D --topic T
    [--mode als|svm] [--minSegments N]`` — one explicit fold pass."""
    from ..core.params import Params
    from .consumer import parse_als_record, parse_svm_record

    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    journal = Journal(
        params.get_required("journalDir"), params.get_required("topic")
    )
    mode = params.get("mode", "als")
    parse_fn = parse_als_record if mode == "als" else parse_svm_record
    t0 = time.perf_counter()
    stats = compact_journal(
        journal, parse_fn=parse_fn,
        min_segments=params.get_int("minSegments", compact_min_segments()),
    )
    dt = time.perf_counter() - t0
    if stats is None:
        print("[compact] nothing to fold")
        return 0
    rate = stats["rows_in"] / dt if dt > 0 else 0.0
    print(
        f"[compact] folded {stats['segments_folded']} segments: "
        f"{stats['rows_in']} -> {stats['rows_out']} rows "
        f"({stats['bytes_reclaimed']} B reclaimed) in {dt:.3f}s "
        f"({rate:,.0f} rows/s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(_main())
