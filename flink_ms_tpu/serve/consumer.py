"""Serving job — counterpart of ``ALSKafkaConsumer`` / ``SVMKafkaConsumer``
(``als-ms/.../qs/ALSKafkaConsumer.java``, ``svm-ms/.../qs/SVMKafkaConsumer.java``).

Pipeline parity (ALSKafkaConsumer.java:26-92):

    journal topic  ->  poll  ->  parse row  ->  keyed put into the sharded
    model table    ->  table is queryable through the lookup server

with the reference's operational envelope re-built natively:

- periodic checkpointing, max 1 concurrent (:44-46): a timer thread writes
  (table snapshot, journal offset) through the selected state backend;
- fixed-delay restart (3 attempts, 10 s — :48-51): the consume loop is
  wrapped in a restart supervisor that restores the last checkpoint and
  replays the journal from the committed offset (at-least-once; duplicate
  rows are last-writer-wins like ``ValueState``);
- state backends (:53-65): ``memory`` (snapshots held in RAM),
  ``fs`` (snapshot dirs under --checkpointDataUri), ``rocksdb`` (the C++
  persistent store when built, otherwise falls back to ``fs`` with a
  warning — same selection flag surface).

Key derivation:
- ALS rows ``id,T,factors`` -> key ``"<id>-<T>"`` (ALSKafkaConsumer.java:75-82)
- SVM rows ``first,rest``   -> key = raw first CSV token (featureID or
  bucket — SVMKafkaConsumer.java:74-82)
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time
import uuid
from typing import Callable, List, Optional, Tuple

from ..core.formats import CHUNK_ALS, CHUNK_SVM, split_journal_chunk
from ..core.params import Params
from ..obs import metrics as obs_metrics
from ..obs import profiler as obs_profiler
from ..obs import tracing as obs_tracing
from . import snapshot as snapshot_mod
from .journal import Journal, OffsetTruncatedError
from .server import LookupServer
from .table import ModelTable, _fnv1a_batch

ALS_STATE = "ALS_MODEL"
SVM_STATE = "SVM_MODEL"


def parse_als_record(line: str) -> Tuple[str, str]:
    id_, typ, payload = line.split(",", 2)
    return f"{id_}-{typ}", payload


def parse_svm_record(line: str) -> Tuple[str, str]:
    key, _, payload = line.partition(",")
    return key, payload


# native bulk-ingest mode ids (tpums_ingest_buf mirrors these parsers
# byte-for-byte; tests pin the parity)
parse_als_record.native_mode = 0
parse_svm_record.native_mode = 1
# columnar chunk-parse mode ids (core.formats.split_journal_chunk mirrors
# these parsers line-for-line; tests pin the parity).  A parse_fn without
# this attribute (custom parsers) always takes the scalar per-line path.
parse_als_record.columnar_mode = CHUNK_ALS
parse_svm_record.columnar_mode = CHUNK_SVM


# ---------------------------------------------------------------------------
# state backends
# ---------------------------------------------------------------------------

class MemoryStateBackend:
    """Snapshots kept in process RAM — survives consume-loop restarts inside
    the job, lost on process death (MemoryStateBackend parity)."""

    kind = "memory"

    def __init__(self):
        self._snap: Optional[Tuple[int, List[dict]]] = None

    def snapshot(self, table: ModelTable, offset: int) -> None:
        if not hasattr(table, "_shards"):
            # arena table: the rows live in the mmap'd file and survive a
            # consume-loop restart on their own — the offset marker is the
            # whole snapshot (replay from it is LWW-idempotent)
            table.flush()
            self._snap = (offset, None)
            return
        with table._lock:
            self._snap = (offset, [dict(s) for s in table._shards])

    def restore(self, table: ModelTable) -> Optional[int]:
        if self._snap is None:
            return None
        offset, shards = self._snap
        if shards is None:
            return offset
        with table._lock:
            table._shards = [dict(s) for s in shards]
        return offset


class FsStateBackend:
    """Snapshot dirs under the checkpoint URI (FsStateBackend parity)."""

    kind = "fs"

    def __init__(self, checkpoint_uri: str):
        import os

        self.dir = checkpoint_uri
        os.makedirs(self.dir, exist_ok=True)

    def snapshot(self, table: ModelTable, offset: int) -> None:
        table.snapshot(self.dir, offset)

    def restore(self, table: ModelTable) -> Optional[int]:
        return table.restore(self.dir)


def make_backend(kind: str, checkpoint_uri: Optional[str]):
    if kind == "memory":
        return MemoryStateBackend()
    if kind == "fs":
        if not checkpoint_uri:
            raise ValueError("fs state backend requires --checkpointDataUri")
        return FsStateBackend(checkpoint_uri)
    if kind == "rocksdb":
        if not checkpoint_uri:
            raise ValueError("rocksdb state backend requires --checkpointDataUri")
        from .native_store import StoreLockedError

        try:
            from .native_store import NativeStateBackend

            return NativeStateBackend(checkpoint_uri)
        except StoreLockedError:
            # another serving job owns this store dir — degrading to fs
            # snapshots in the SAME dir would silently fork the state
            raise
        except Exception as e:
            # toolchain missing / build failed: fs snapshots still honor the
            # checkpoint contract
            print(
                f"[serve] native store unavailable ({e}); rocksdb mode "
                "falling back to fs snapshots",
                file=sys.stderr,
            )
            return FsStateBackend(checkpoint_uri)
    raise ValueError(f"unknown state backend: {kind} (use rocksdb|fs|memory)")


# ---------------------------------------------------------------------------
# the job
# ---------------------------------------------------------------------------

class ServingJob:
    def __init__(
        self,
        journal: Journal,
        state_name: str,
        parse_fn: Callable[[str], Tuple[str, str]],
        backend,
        n_shards: int = 8,
        checkpoint_interval_ms: int = 60_000,
        poll_interval_s: float = 0.1,
        host: str = "0.0.0.0",
        port: int = 6123,
        job_id: Optional[str] = None,
        restart_attempts: int = 3,
        restart_delay_s: float = 10.0,
        native_server: bool = False,
        start_from: str = "earliest",
        ingest_mode: Optional[str] = None,
        topk_index: bool = True,
        replica_of: Optional[str] = None,
        replica_index: Optional[int] = None,
        topology_group: Optional[str] = None,
        generation: Optional[int] = None,
        snapshots: Optional[bool] = None,
        snapshot_min_bytes: Optional[int] = None,
        compact: Optional[bool] = None,
        table: Optional[str] = None,
    ):
        if start_from not in ("earliest", "latest"):
            raise ValueError("start_from must be earliest|latest")
        # journal->state application strategy (TPUMS_INGEST_MODE / CLI
        # --ingestMode): "columnar" splits whole byte chunks with numpy and
        # applies them through put_many_columns; "scalar" is the per-line
        # reference path; "auto" (default) picks columnar whenever the
        # parser advertises a columnar_mode.  The native C++ bulk path
        # (no listeners + rocksdb table) outranks both.
        if ingest_mode is None:
            ingest_mode = os.environ.get("TPUMS_INGEST_MODE", "auto")
        if ingest_mode not in ("auto", "columnar", "scalar"):
            raise ValueError("ingest_mode must be auto|columnar|scalar")
        self.ingest_mode = ingest_mode
        self.journal = journal
        self.state_name = state_name
        self.host = host
        self.parse_fn = parse_fn
        self.backend = backend
        # which table implementation holds the factors (--table /
        # TPUMS_TABLE): "dict" is the in-RAM sharded ModelTable (or the
        # backend's own durable table for rocksdb); "arena" is the
        # shared-memory mmap arena (serve/arena.py) the C++ server and
        # the snapshotter read zero-copy.  Fleet members — sharded
        # (shard_filter), HA replicas (replica_of), elastic topologies
        # (topology_group/generation) — DEFAULT to arena now that its
        # write path is native (ROADMAP item 1); TPUMS_TABLE=dict opts
        # out.  Standalone jobs and make_table backends (rocksdb owns
        # its durable table) keep their existing default.
        _sf = getattr(parse_fn, "shard_filter", None)
        if table is None:
            table = os.environ.get("TPUMS_TABLE")
        if table is None:
            fleet = (_sf is not None or replica_of is not None
                     or topology_group is not None
                     or generation is not None)
            table = "arena" if fleet and not hasattr(
                backend, "make_table") else "dict"
        if table not in ("dict", "arena"):
            raise ValueError("table must be dict|arena")
        self.table_kind = table
        self._snap_owner = (int(_sf[0]), int(_sf[1])) if _sf else (0, 1)
        if table == "arena":
            from .arena import ArenaModelTable

            # one writer per arena (flock): the dir is disambiguated along
            # every axis a fleet multiplies on over a shared journal —
            # state name, worker shard, replica index, topology generation
            arena_dir = os.path.join(
                journal.dir,
                "{}.arena-{}-w{}of{}-r{}-g{}".format(
                    journal.topic, state_name, self._snap_owner[0],
                    self._snap_owner[1], replica_index or 0,
                    generation or 0),
            )
            self.table = ArenaModelTable(n_shards, dir=arena_dir)
        # the native (rocksdb-parity) backend provides its own durable table;
        # memory/fs back a plain in-RAM sharded table
        elif hasattr(backend, "make_table"):
            self.table = backend.make_table(n_shards)
        else:
            self.table = ModelTable(n_shards)
        self.checkpoint_interval_s = checkpoint_interval_ms / 1000.0
        self.poll_interval_s = poll_interval_s
        self.job_id = job_id or uuid.uuid4().hex
        self.restart_attempts = restart_attempts
        self.restart_delay_s = restart_delay_s
        # Kafka auto.offset.reset parity for a consumer with no committed
        # checkpoint: earliest replays the whole retained topic, latest
        # serves only rows published after this job came up (aligned to
        # the last record boundary — a producer mid-append must not make
        # the first poll start inside its torn line).  A restored
        # checkpoint always wins (start() overwrites).
        self.offset = (
            journal.aligned_end_offset() if start_from == "latest" else 0
        )
        # the supervised-restart fallback replays from here when no
        # checkpoint exists yet: a startFrom=latest job must not reset to 0
        # and replay the whole retained backlog it was configured to skip
        self._seed_offset = self.offset
        self.parse_errors = 0
        # ingest-plane observability: which path ran last, how many rows /
        # chunks it applied, and the wall time spent inside state
        # application (parse + put + listener fan-out) — the bench's
        # cold-start rows/sec and the ingest_profile tool read these
        self.ingest_path = "idle"
        self.ingest_rows = 0
        self.ingest_batches = 0
        self.ingest_apply_s = 0.0
        self.checkpoints_deferred = 0
        # snapshot-shipped bootstrap (serve/snapshot.py): durable columnar
        # per-shard snapshot artifacts published at checkpoint cadence; a
        # (re)starting job bulk-loads the newest valid one and replays only
        # the journal tail behind it — O(state) recovery instead of
        # O(history) replay.  The native (rocksdb) table IS its own durable
        # O(state) artifact, so snapshots apply to the in-RAM tables only.
        if snapshots is None:
            snapshots = os.environ.get("TPUMS_SNAPSHOTS", "1") != "0"
        self._snapshots_on = bool(snapshots) and (
            hasattr(self.table, "_shards") or self.table_kind == "arena"
        )
        self._snap_root = snapshot_mod.snapshot_root(journal.dir, journal.topic)
        if snapshot_min_bytes is None:
            try:
                snapshot_min_bytes = int(
                    os.environ.get("TPUMS_SNAPSHOT_MIN_BYTES", 1 << 20)
                )
            except ValueError:
                snapshot_min_bytes = 1 << 20
        self._snap_min_bytes = max(int(snapshot_min_bytes), 1)
        self._last_snap_offset = 0
        self.bootstrap_source = "replay"
        self.bootstrap_seconds: Optional[float] = None
        self._bootstrap_t0: Optional[float] = None
        # background journal compactor (serve/compact.py): the journal is
        # shared, so exactly one member per fleet folds it — worker 0 of
        # replica 0 (a solo job qualifies).  Elastic jobs additionally
        # stand the thread down per-tick unless their generation is the
        # group's ACTIVE one (_compactor_active): during a cutover, gen g
        # and the warming gen g+1 both have a worker 0 on the same journal
        if compact is None:
            from .compact import compact_enabled

            compact = compact_enabled()
        self._compact_on = (
            bool(compact)
            and self._snap_owner[0] == 0
            and replica_index in (None, 0)
        )
        self._compactor = None
        # registry instruments (obs/): the ingest plane as scrapeable
        # series — labeled by state name only (a replica fleet is one job
        # per process; in-process test jobs share series and assert deltas)
        reg = obs_metrics.get_registry()
        st = state_name
        self._obs_rows = reg.counter("tpums_ingest_rows_total", state=st)
        self._obs_batches = reg.counter(
            "tpums_ingest_batches_total", state=st)
        self._obs_parse_errors = reg.counter(
            "tpums_ingest_parse_errors_total", state=st)
        self._obs_apply = reg.histogram(
            "tpums_ingest_apply_seconds", state=st)
        self._obs_backlog = reg.gauge(
            "tpums_journal_backlog_bytes", state=st)
        self._obs_rows_per_s = reg.gauge("tpums_ingest_rows_per_s", state=st)
        self._obs_ckpt = reg.counter("tpums_checkpoints_total", state=st)
        self._obs_ckpt_deferred = reg.gauge(
            "tpums_checkpoints_deferred", state=st)
        self._obs_ready_flips = reg.counter(
            "tpums_ready_transitions_total", state=st)
        # bootstrap/snapshot plane: how long a (re)start took to ready,
        # which source fed it, restore failures that used to be swallowed
        self._obs_restore_fail = reg.counter(
            "tpums_checkpoint_restore_failures_total", state=st)
        self._obs_bootstrap_s = reg.histogram(
            "tpums_bootstrap_seconds", state=st)
        self._obs_snap_age = reg.gauge(
            "tpums_snapshot_age_seconds", state=st)
        self._obs_snap_pub = reg.counter(
            "tpums_snapshots_published_total", state=st)
        self._obs_snap_restore_fail = reg.counter(
            "tpums_snapshot_restore_failures_total", state=st)
        self._obs_truncated = reg.counter(
            "tpums_journal_truncated_total", state=st)
        # HA plane (serve/ha.py): membership in a replica set, announced
        # through the registry so clients and supervisors can resolve the
        # whole set by the logical shard-group id
        self.replica_of = replica_of
        self.replica_index = replica_index
        # elastic plane (serve/elastic.py): a worker belonging to topology
        # generation `generation` of group `topology_group` advertises both
        # through HEALTH, plus the group's ACTIVE generation as observed at
        # heartbeat time — clients use active != ours as the re-resolve
        # hint without any new wire verb (the HEALTH JSON is the channel)
        self.topology_group = topology_group
        self.generation = generation
        self._observed_topology_gen: Optional[int] = generation
        # readiness gate: False until the consume loop has replayed the
        # journal backlog that existed when it came up — a rejoining
        # replica must never be routed traffic over a half-replayed table
        self._ready = threading.Event()
        self._hb_lock = threading.Lock()
        self._stopped = False
        self._stop = threading.Event()
        self._consumer_thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._native_arena = None
        if native_server:
            # C++ epoll data plane reading the persistent store directly —
            # requires the native (rocksdb) backend, which owns the store,
            # OR the shared-memory arena table, which the server maps
            # read-only (zero per-row pushes; tag-dispatched handle)
            from .native_store import NativeLookupServer

            if self.table_kind == "arena":
                from .native_store import NativeArena

                self._native_arena = NativeArena(self.table.dir)
                serve_handle = self._native_arena
            elif hasattr(backend, "store"):
                serve_handle = backend.store
            else:
                # either the wrong backend kind was requested, or rocksdb WAS
                # requested but degraded to fs because the native build is
                # unavailable (make_backend printed the cause)
                raise ValueError(
                    "--nativeServer needs the native (rocksdb) store, but the "
                    f"active backend is '{backend.kind}' — pass --stateBackend "
                    "rocksdb, and if you did, the native store failed to load "
                    "(see the warning above for the build error)"
                )
            self.server = NativeLookupServer(
                serve_handle, state_name, job_id=self.job_id,
                host=host, port=port,
                # ALS planes serve the full verb set natively: TOPK/TOPKV
                # score the "-I" catalog straight from the store (the
                # Python plane's DeviceFactorIndex analog, C++-side)
                topk_suffixes=("-I", "-U") if state_name == ALS_STATE
                else None,
            )
        else:
            topk_handlers = {}
            if state_name == ALS_STATE and topk_index:
                # device-scored top-k over the live item factors (serve/topk.py)
                from .topk import make_als_topk_handler

                topk_handlers[state_name] = make_als_topk_handler(self.table)
            self.server = LookupServer(
                {state_name: self.table},
                host=host,
                port=port,
                job_id=self.job_id,
                topk_handlers=topk_handlers,
                health_fn=self.health,
                staleness_fn=self._staleness,
            )
        self.port = self.server.port

    def _staleness(self):
        """Replication staleness for st=-opted reads: the follower
        replicator's journal-dir status record (serve/georepl.py), or None
        (-> 0.000 on the wire) when this journal is not a geo follower."""
        from . import georepl

        return georepl.staleness_of(self.journal.dir, self.journal.topic)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingJob":
        self._bootstrap_t0 = time.monotonic()
        restored = None
        try:
            restored = self.backend.restore(self.table)
        except Exception as e:
            # a corrupt/missing checkpoint is a counted event, not a crash:
            # bootstrap falls down the chain (snapshot, else full replay)
            self._obs_restore_fail.inc()
            print(
                f"[serve:{self.state_name}] checkpoint restore failed "
                f"({e}); falling back to snapshot/replay bootstrap",
                file=sys.stderr,
            )
        if restored is not None:
            self.offset = restored
            self.bootstrap_source = "checkpoint"
            print(
                f"[serve:{self.state_name}] restored {len(self.table)} rows, "
                f"journal offset {self.offset}",
                file=sys.stderr,
            )
        # snapshot overlay: a published snapshot AHEAD of the checkpoint
        # (or of offset 0) replaces that much replay with one columnar
        # bulk-load; last-writer-wins overlay keeps a checkpoint-restored
        # table convergent
        info = self._try_snapshot_bootstrap(min_offset=self.offset + 1)
        if info is not None:
            self.offset = info["offset"]
            self._last_snap_offset = info["offset"]
            self.bootstrap_source = "snapshot"
            if info.get("age_s") is not None:
                self._obs_snap_age.set(info["age_s"])
            print(
                f"[serve:{self.state_name}] snapshot bootstrap: "
                f"{info['rows']} rows from {info['members']} member(s), "
                f"tail replay from offset {self.offset}",
                file=sys.stderr,
            )
        self.server.start()
        # continuous profiling is part of serving (Google-Wide-Profiling
        # stance): the process-wide sampler starts with the first job and
        # is shared by all of them; TPUMS_PROF=0 is the kill switch
        obs_profiler.ensure_started()
        # announce jobId -> endpoint so clients resolve this job without
        # explicit port wiring (the reference's JobManager lookup,
        # QueryClientHelper.java:82-92; best-effort by design), with a
        # heartbeat contract: the entry promises a refresh within the TTL,
        # so readers can treat a silent job as dead (serve/ha.py)
        self._heartbeat_now()
        self._consumer_thread = threading.Thread(
            target=self._supervised_consume, name="journal-consumer", daemon=True
        )
        self._consumer_thread.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="registry-heartbeat", daemon=True
        )
        self._hb_thread.start()
        if self._compact_on:
            from .compact import CompactorThread

            # shares this job's stop event, so it stands down with stop()
            self._compactor = CompactorThread(
                self.journal, self.parse_fn, stop_event=self._stop,
                active_fn=self._compactor_active,
            )
            self._compactor.start()
        return self

    # -- liveness / readiness (HA plane surface) ---------------------------

    @property
    def ready(self) -> bool:
        """True once the consume loop has caught up with the journal end
        observed at (re)start — the gate a rejoining replica passes before
        it may serve traffic."""
        return self._ready.is_set()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return self._ready.wait(timeout)

    def backlog_bytes(self) -> int:
        """Unconsumed journal bytes behind the producer's end offset."""
        try:
            return max(self.journal.end_offset() - self.offset, 0)
        except OSError:
            return 0

    def health(self) -> dict:
        """The HEALTH verb's payload (key count is added server-side)."""
        ready = self.ready
        payload = {
            "state": self.state_name,
            "job_id": self.job_id,
            "ready": ready,
            "status": "ready" if ready else "replaying",
            "backlog_bytes": self.backlog_bytes(),
            "offset": self.offset,
            "ingest_path": self.ingest_path,
            "replica_of": self.replica_of,
            "replica": self.replica_index,
            "topology_group": self.topology_group,
            "generation": self.generation,
            "topology_gen": self._observed_topology_gen,
            "bootstrap_source": self.bootstrap_source,
            "bootstrap_seconds": self.bootstrap_seconds,
        }
        alerts = self._alert_hint()
        if alerts is not None:
            # same opt-in discipline as tn=/tid=: the fields appear ONLY
            # when a watcher has published a fresh alert record (and the
            # TPUMS_WATCH_HEALTH_HINT kill switch is not thrown), so a
            # fleet without a watch loop keeps its HEALTH bytes unchanged
            payload["alerts_firing"] = alerts["firing"]
            payload["alerts_max_severity"] = alerts["max_severity"]
        return payload

    # HEALTH is a hot poll path (supervisors, elastic clients): cache the
    # registry alert-record read for ~1s rather than hitting the
    # filesystem per reply
    _ALERT_HINT_TTL_S = 1.0

    def _alert_hint(self) -> Optional[dict]:
        if os.environ.get("TPUMS_WATCH_HEALTH_HINT", "1") == "0":
            return None
        now = time.time()
        cached = getattr(self, "_alert_hint_cache", None)
        if cached is not None and now - cached[0] < self._ALERT_HINT_TTL_S:
            return cached[1]
        from . import registry

        try:
            rec = registry.resolve_alerts()
        except Exception:  # noqa: BLE001 - hint must never break HEALTH
            rec = None
        self._alert_hint_cache = (now, rec)
        return rec

    # -- snapshot bootstrap / publication (serve/snapshot.py) --------------

    def _try_snapshot_bootstrap(
        self, min_offset: int = 0, max_offset: Optional[int] = None
    ) -> Optional[dict]:
        """Bulk-load the newest valid snapshot covering this worker's key
        slice (fallback chain: bad checksum -> older snapshot -> None, and
        the caller replays the journal instead).  Corrupt members are
        counted in ``tpums_snapshot_restore_failures_total``."""
        if not self._snapshots_on:
            return None
        try:
            return snapshot_mod.bootstrap(
                self.table,
                self._snap_root,
                owner=self._snap_owner,
                min_offset=min_offset,
                max_offset=max_offset,
                on_corrupt=lambda m: self._obs_snap_restore_fail.inc(),
            )
        except Exception as e:
            # never let the bootstrap fast path kill a job that could have
            # replayed its way up instead
            print(
                f"[serve:{self.state_name}] snapshot bootstrap failed "
                f"({e}); replaying journal",
                file=sys.stderr,
            )
            return None

    def _maybe_publish_snapshot(self) -> None:
        """Publish a snapshot artifact at the current (table, offset) —
        called between chunks (same consistency point as a checkpoint) once
        at least ``snapshot_min_bytes`` of journal landed since the last
        one."""
        if not self._snapshots_on or self.offset <= 0:
            return
        if self.offset - self._last_snap_offset < self._snap_min_bytes:
            return
        try:
            manifest = snapshot_mod.publish(
                self._snap_root,
                self.table,
                self.offset,
                shard=self._snap_owner[0],
                num_shards=self._snap_owner[1],
                group=self.topology_group,
                gen=self.generation,
                topic=self.journal.topic,
            )
        except Exception as e:
            print(
                f"[serve:{self.state_name}] snapshot publish failed ({e})",
                file=sys.stderr,
            )
            return
        self._last_snap_offset = self.offset
        self._obs_snap_pub.inc()
        self._obs_snap_age.set(0.0)
        obs_tracing.event(
            "snapshot_published", state=self.state_name, job_id=self.job_id,
            offset=self.offset, rows=manifest["rows"],
            shard=self._snap_owner[0], num_shards=self._snap_owner[1])

    def _recover_truncated(self, err: OffsetTruncatedError) -> int:
        """The consume loop hit journal history that no longer exists
        byte-for-byte.  Returns the offset to resume from; the table stays
        convergent on every path (last-writer-wins re-application)."""
        self._obs_truncated.inc()
        if err.lossless:
            # a fold replaced bytes we were mid-way through: re-reading the
            # compacted prefix from its base is a last-writer-wins superset
            # of what we already applied — zero loss
            self.journal.compacted_rereads += 1
            print(
                f"[serve:{self.state_name}] journal compacted under us at "
                f"{err.offset}; re-reading fold from {err.resume_offset}",
                file=sys.stderr,
            )
            return err.resume_offset
        # rows below resume_offset are GONE (retention); only a snapshot
        # that reaches the retained region (offset >= resume_offset) covers
        # the hole with zero loss.  One below resume_offset must NOT be
        # resumed from — its offset points back into the hole, so the next
        # read re-raises this same truncation and the loop livelocks
        info = self._try_snapshot_bootstrap(min_offset=err.resume_offset)
        if info is not None:
            self._last_snap_offset = max(
                self._last_snap_offset, info["offset"])
            print(
                f"[serve:{self.state_name}] offset {err.offset} expired; "
                f"snapshot covers through {info['offset']}",
                file=sys.stderr,
            )
            return info["offset"]
        # a snapshot strictly inside the hole can't be resumed from, but
        # bulk-loading it still narrows the loss: state through its offset
        # is covered, and only (snapshot offset, resume_offset) is gone
        info = self._try_snapshot_bootstrap(
            min_offset=err.offset + 1, max_offset=err.resume_offset)
        if info is not None:
            self._last_snap_offset = max(
                self._last_snap_offset, info["offset"])
        base = info["offset"] if info is not None else err.offset
        # resume with an explicit, counted gap — the pre-typed-error
        # journal behavior, now impossible to hit silently
        lost = err.resume_offset - base
        self.journal.expired_bytes_skipped += lost
        print(
            f"[serve:{self.state_name}] offset {err.offset} expired; no "
            f"snapshot reaches retained offset {err.resume_offset}; "
            f"skipping {lost} lost bytes (state covered through {base})",
            file=sys.stderr,
        )
        return err.resume_offset

    def _compactor_active(self) -> bool:
        """Per-tick compactor gate (CompactorThread ``active_fn``): True
        when this worker's topology generation is the group's ACTIVE one,
        as observed at heartbeat time.  During an elastic cutover both
        gen g and the warming gen g+1 have a worker 0 on the shared
        journal; the warming fleet stands down until its generation is
        published active (and the retired fleet stands down right after),
        keeping the one-compactor-per-journal invariant.  Non-elastic
        jobs always qualify."""
        if self.topology_group is None or self.generation is None:
            return True
        obs = self._observed_topology_gen
        return obs is None or int(obs) == int(self.generation)

    def _heartbeat_now(self) -> None:
        from . import registry

        # the lock makes read-ready + register atomic: without it the
        # heartbeat thread can read ready=False, lose the CPU, and write
        # that stale value AFTER the consume loop registered ready=True —
        # readiness must be monotone once flipped.  The stop check under
        # the same lock pairs with the locked unregister in stop(): the
        # consume loop's ready-flip refresh must not resurrect an entry a
        # concurrent shutdown just removed
        with self._hb_lock:
            if self._stop.is_set():
                return
            registry.register(
                self.job_id, self.host, self.port, self.state_name,
                replica_of=self.replica_of, replica=self.replica_index,
                ready=self.ready, ttl_s=registry.replica_ttl_s(),
            )
        if self.topology_group:
            # piggyback on the heartbeat cadence: one small registry read
            # keeps the generation-changed hint served by HEALTH fresh
            # within a heartbeat interval of a cutover
            try:
                topo = registry.resolve_topology(self.topology_group)
                if topo is not None:
                    self._observed_topology_gen = int(topo["gen"])
            except Exception:
                pass
        set_health = getattr(self.server, "set_health", None)
        if set_health is not None:
            # native plane: the C++ server has no callback into this job,
            # so the HEALTH report is PUSHED on the heartbeat cadence (the
            # ready flip triggers an immediate heartbeat, so readiness
            # reaches the wire without waiting out an interval); the server
            # splices in the live key count and metrics_uri itself
            try:
                import json as _json

                set_health(_json.dumps(self.health()))
            except Exception:
                pass

    def _heartbeat_loop(self) -> None:
        from . import registry

        interval = registry.heartbeat_interval_s()
        while not self._stop.wait(interval):
            if self._stop.is_set():
                break
            self._heartbeat_now()

    def stop(self) -> None:
        # idempotent: wait() calls stop() on every exit path (SIGTERM
        # handler, KeyboardInterrupt, supervisor give-up), and callers may
        # also stop() explicitly
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        # join the heartbeat BEFORE unregistering, or an in-flight refresh
        # could resurrect the entry we just removed (it would linger until
        # TTL expiry instead of vanishing with the job)
        if self._hb_thread:
            self._hb_thread.join(timeout=5)
        from . import registry

        # under _hb_lock: the consumer thread is NOT joined yet, and its
        # ready-flip heartbeat would otherwise race this removal
        with self._hb_lock:
            registry.unregister(self.job_id)
        if self._consumer_thread:
            self._consumer_thread.join(timeout=10)
        self.server.stop()
        if self._native_arena is not None:
            # after server.stop(): no reader thread may touch the mapping
            self._native_arena.close()
        if self.table_kind == "arena" and (
            self._consumer_thread is None
            or not self._consumer_thread.is_alive()
        ):
            # releases the writer flock; a wedged consumer thread leaks the
            # mapping instead (the flock dies with the process)
            self.table.close()
        if hasattr(self.backend, "close"):
            # never free the native store under a still-running consumer
            # thread (use-after-free); a wedged thread leaks the handle
            # instead, and the flock dies with the process
            if self._consumer_thread is None or not self._consumer_thread.is_alive():
                self.backend.close()
            else:
                print(
                    f"[serve:{self.state_name}] consumer thread still busy; "
                    "leaving native store open",
                    file=sys.stderr,
                )

    def wait(self) -> None:
        # CLI foreground mode: translate SIGTERM into an orderly stop()
        # so the registry entry and backing store are released (a killed
        # job would otherwise leave a stale jobId -> port entry; clients
        # then see a refused connect instead of a clean miss)
        import signal

        try:
            signal.signal(signal.SIGTERM, lambda *_: self.stop())
        except ValueError:
            pass  # not the main thread: caller owns signal handling
        try:
            while not self._stop.is_set():
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        # every exit path releases the registry entry and backing store
        # (idempotent — a SIGTERM-handler stop() already ran is a no-op);
        # this also covers the supervisor's give-up path, which sets
        # _stop without the full teardown
        self.stop()

    # -- consume loop with fixed-delay restart -----------------------------

    def _supervised_consume(self) -> None:
        attempts = 0
        while not self._stop.is_set():
            try:
                self._consume_loop()
                return  # clean stop
            except Exception as e:
                attempts += 1
                obs_tracing.events_counter(
                    "consume_restart" if attempts <= self.restart_attempts
                    else "consume_giveup",
                    state=self.state_name, job_id=self.job_id,
                    attempt=attempts, error=str(e))
                if attempts > self.restart_attempts:
                    print(
                        f"[serve:{self.state_name}] giving up after "
                        f"{self.restart_attempts} restarts: {e}",
                        file=sys.stderr,
                    )
                    # a dead job must not stay resolvable: drop the
                    # registry entry here too — embedded (non-CLI) jobs
                    # have no wait() to run the full stop() for them.
                    # _stop is set FIRST so the heartbeat loop stands down
                    # (a refresh racing this unregister would linger only
                    # until TTL expiry — the registry's backstop)
                    self._stop.set()
                    from . import registry

                    with self._hb_lock:
                        registry.unregister(self.job_id)
                    return
                print(
                    f"[serve:{self.state_name}] consume loop failed ({e}); "
                    f"restart {attempts}/{self.restart_attempts} in "
                    f"{self.restart_delay_s}s",
                    file=sys.stderr,
                )
                if self._stop.wait(self.restart_delay_s):
                    return
                try:
                    restored = self.backend.restore(self.table)
                    self.offset = (
                        restored if restored is not None else self._seed_offset
                    )
                except Exception as re:
                    # a corrupt/missing checkpoint must not kill the
                    # supervisor thread; continue from the in-memory state
                    # (at-least-once replay keeps the table convergent)
                    self._obs_restore_fail.inc()
                    print(
                        f"[serve:{self.state_name}] checkpoint restore failed "
                        f"({re}); continuing from in-memory state at offset "
                        f"{self.offset}",
                        file=sys.stderr,
                    )

    # a wall-clock checkpoint is deferred while a replay backlog is live
    # (every poll still moving >= half a chunk cap of bytes), but never past
    # this many checkpoint intervals — bounds the at-least-once replay debt
    # a crash mid-replay can accumulate
    CHECKPOINT_MAX_DEFER_INTERVALS = 5.0
    # one ingest poll's byte budget (both native and columnar paths): caps
    # how long one state-application critical section can run
    CHUNK_CAP = 2 << 20

    def _consume_loop(self) -> None:
        last_checkpoint = time.time()
        chunk_cap = self.CHUNK_CAP
        # readiness target: the journal end when this loop came up.  Until
        # the offset passes it, the table is mid-replay and the job reports
        # "replaying" (registry ready=False) so no failover routes here.
        # A supervised RESTART inside a live process keeps ready set — the
        # table stayed warm and the server kept answering throughout.
        ready_target = self.journal.end_offset() if not self.ready else 0
        while not self._stop.is_set():
            # native fast path: rocksdb-parity table + a standard parser +
            # no change listeners -> the whole chunk (parse, key-derive,
            # put) runs in ONE C++ call; listeners (top-k dirty tracking)
            # force the Python path so they keep seeing every key.  The
            # chunk is capped at 2 MiB (~15k rows) because the ingest call
            # holds the store mutex the C++ lookup server's reads take —
            # same starvation bound as the Python path's row-sliced chunks.
            native_mode = getattr(self.parse_fn, "native_mode", None)
            columnar_mode = getattr(self.parse_fn, "columnar_mode", None)
            rows_before = self.ingest_rows
            errs_before = self.parse_errors
            t0 = time.perf_counter()
            try:
                if (
                    native_mode is not None
                    and hasattr(self.table, "ingest_lines")
                    and not getattr(self.table, "_listeners", True)
                ):
                    self.ingest_path = "native"
                    chunk, next_offset = self.journal.read_bytes_from(
                        self.offset, max_bytes=chunk_cap
                    )
                    got_any = bool(chunk)
                    if chunk:
                        rows, errs = self.table.ingest_lines(
                            chunk, native_mode)
                        self.parse_errors += errs
                        self.ingest_rows += rows
                        self.ingest_batches += 1
                elif columnar_mode is not None and self.ingest_mode != "scalar":
                    # columnar path: numpy splits the whole byte chunk into
                    # key/value columns, ownership filtering and shard routing
                    # are vectorized, and listeners get ONE batched callback
                    self.ingest_path = "columnar"
                    chunk, next_offset = self.journal.read_bytes_from(
                        self.offset, max_bytes=chunk_cap
                    )
                    got_any = bool(chunk)
                    if chunk:
                        self._apply_chunk_columnar(chunk, columnar_mode)
                        self.ingest_batches += 1
                else:
                    self.ingest_path = "scalar"
                    lines, next_offset = self.journal.read_from(
                        self.offset, max_bytes=chunk_cap
                    )
                    got_any = bool(lines)
                    if lines:
                        self._apply_lines(lines)
                        self.ingest_batches += 1
            except OffsetTruncatedError as err:
                # our offset points at folded or expired history: recover
                # (compacted re-read / snapshot / counted gap) and poll again
                self.offset = self._recover_truncated(err)
                continue
            if got_any:
                dt = time.perf_counter() - t0
                self.ingest_apply_s += dt
                if obs_metrics.metrics_enabled():
                    rows = self.ingest_rows - rows_before
                    self._obs_rows.inc(rows)
                    self._obs_batches.inc(1)
                    self._obs_parse_errors.inc(
                        self.parse_errors - errs_before)
                    self._obs_apply.observe(dt)
                    if dt > 0:
                        self._obs_rows_per_s.set(rows / dt)
            bytes_advanced = next_offset - self.offset
            self.offset = next_offset
            if got_any and obs_metrics.metrics_enabled():
                # journal lag behind the producer's end offset — the gauge
                # a scrape reads to see a replica falling behind.  Only
                # polls that ingested re-stat the journal: backlog can
                # only change when the producer appends, and the very
                # next poll reads that — an idle caught-up loop pays no
                # per-poll stat (it would steal GIL slices from the
                # serving threads for a gauge that cannot have moved)
                self._obs_backlog.set(self.backlog_bytes())
            if not self._ready.is_set() and (
                not got_any or self.offset >= ready_target
            ):
                # caught up with the backlog that existed at start: flip to
                # ready and push the flag to the registry immediately (the
                # heartbeat cadence would otherwise delay failback by up to
                # one interval)
                self._ready.set()
                if self._bootstrap_t0 is not None:
                    # cold-path bookkeeping, once per process lifetime: how
                    # long start()->ready took and which source fed it —
                    # the flatness the serving_bootstrap bench tracks
                    self.bootstrap_seconds = (
                        time.monotonic() - self._bootstrap_t0
                    )
                    self._bootstrap_t0 = None
                    self._obs_bootstrap_s.observe(self.bootstrap_seconds)
                    obs_metrics.get_registry().counter(
                        "tpums_bootstrap_total", state=self.state_name,
                        kind=self.bootstrap_source).inc()
                self._heartbeat_now()
                self._obs_ready_flips.inc()
                obs_tracing.event(
                    "ready", state=self.state_name, job_id=self.job_id,
                    offset=self.offset, replica_of=self.replica_of,
                    replica=self.replica_index,
                    source=self.bootstrap_source)
                # a fresh snapshot right at ready makes the NEXT joiner's
                # bootstrap O(state) even before a checkpoint interval
                # elapses (min-bytes gated, so a snapshot-fed start that
                # replayed a short tail won't immediately republish)
                self._maybe_publish_snapshot()
            now = time.time()
            if now - last_checkpoint >= self.checkpoint_interval_s:
                # a full-chunk poll means we're inside a cold-start replay
                # backlog: snapshotting the whole table now would stall
                # ingest behind a multi-second critical section and commit
                # an offset we'll blow past within milliseconds — defer,
                # bounded so a crash can't replay unboundedly
                backlog = got_any and bytes_advanced >= chunk_cap // 2
                overdue = now - last_checkpoint >= (
                    self.checkpoint_interval_s
                    * self.CHECKPOINT_MAX_DEFER_INTERVALS
                )
                if backlog and not overdue:
                    self.checkpoints_deferred += 1
                    self._obs_ckpt_deferred.set(self.checkpoints_deferred)
                else:
                    self.backend.snapshot(self.table, self.offset)
                    last_checkpoint = now
                    self._obs_ckpt.inc()
                    self._maybe_publish_snapshot()
            if not got_any:
                self._stop.wait(self.poll_interval_s)

    def _apply_lines(self, lines) -> None:
        batch = []
        for line in lines:
            if not line:
                continue
            try:
                parsed = self.parse_fn(line)
            except ValueError:
                # the reference would fail the task and burn a restart on
                # a malformed row; skip-and-count is the deliberate fix
                # (SURVEY.md Appendix C decision)
                self.parse_errors += 1
                continue
            if parsed is None:
                continue  # row owned by another sharded worker
            batch.append(parsed)
        # one lock acquisition per chunk, not per row — but chunked so
        # a cold-start replay of a big journal can't starve concurrent
        # queries behind one multi-second critical section
        for s in range(0, len(batch), 10_000):
            self.table.put_many(batch[s:s + 10_000])
        self.ingest_rows += len(batch)

    def _apply_chunk_columnar(self, chunk: bytes, mode: int) -> None:
        """Vectorized equivalent of read_from + _apply_lines: same skipped
        rows, same parse-error counts, same last-writer-wins table state
        (tests pin byte-identical parity against the scalar path).  The
        shard-routing hashes ride along from the chunk parser so neither
        the ownership filter nor the table re-hashes the keys."""
        keys, values, errs, hashes = split_journal_chunk(
            chunk, mode, with_hashes=True
        )
        self.parse_errors += errs
        shard_filter = getattr(self.parse_fn, "shard_filter", None)
        if shard_filter is not None and keys:
            # sharded worker: vectorized ownership filter replaces the
            # per-row "parsed is None" checks of the scalar wrapper
            worker_index, num_workers = shard_filter
            import numpy as np

            if hashes is None:
                hashes = _fnv1a_batch(keys)
            mine = hashes % num_workers == worker_index
            if not mine.all():
                keys = np.asarray(keys, dtype=object)[mine].tolist()
                values = np.asarray(values, dtype=object)[mine].tolist()
                hashes = hashes[mine]
        # row-sliced like the scalar path so one chunk can't starve
        # concurrent queries behind a single table-lock hold (the
        # vectorized apply is ~5x faster per row, hence the larger slice)
        for s in range(0, len(keys), 50_000):
            self.table.put_many_columns(
                keys[s:s + 50_000], values[s:s + 50_000],
                hashes=None if hashes is None else hashes[s:s + 50_000],
            )
        self.ingest_rows += len(keys)

    def ingest_stats(self) -> dict:
        """Ingest-plane counters for benches and monitoring."""
        return {
            "path": self.ingest_path,
            "rows": self.ingest_rows,
            "batches": self.ingest_batches,
            "apply_s": self.ingest_apply_s,
            "parse_errors": self.parse_errors,
            "checkpoints_deferred": self.checkpoints_deferred,
            "offset": self.offset,
        }


# ---------------------------------------------------------------------------
# CLIs
# ---------------------------------------------------------------------------

def _resolve_journal_dir(params: Params) -> str:
    """Accept both the native ``--journalDir`` and the reference's Kafka
    connection flags (``--bootstrap.servers``, ``--zookeeper.connect``,
    ``--group.id`` — ALSKafkaConsumer.java:30-35) so a reference-shaped
    invocation runs unchanged.  ``bootstrap.servers`` naming a filesystem
    path maps to the journal dir (the journal IS the broker here); a
    ``host:port`` value is acknowledged and ignored with a note."""
    if params.has("journalDir"):
        return params.get_required("journalDir")
    bootstrap = params.get("bootstrap.servers")
    looks_like_path = bool(bootstrap) and "://" not in bootstrap and (
        os.path.isdir(bootstrap)
        or bootstrap.startswith(("/", "./", "../"))
    )  # broker URLs (PLAINTEXT://host:9092, host:9092/chroot) fall through
    if looks_like_path:
        print(
            f"[serve] mapping --bootstrap.servers {bootstrap} to the local "
            "journal directory",
            file=sys.stderr,
        )
        return bootstrap
    fallback = os.environ.get(
        "TPUMS_JOURNAL_DIR",
        os.path.join(tempfile.gettempdir(), "flink_ms_tpu_journal"),
    )
    if bootstrap:
        print(
            f"[serve] --bootstrap.servers {bootstrap} names a broker, not a "
            f"path; there is no Kafka here — journal dir: {fallback} "
            "(override with --journalDir or TPUMS_JOURNAL_DIR)",
            file=sys.stderr,
        )
        return fallback
    return params.get_required("journalDir")  # raises the canonical error


def _run_consumer_cli(params: Params, state_name: str, parse_fn) -> ServingJob:
    for ignored in ("zookeeper.connect", "group.id"):
        if params.has(ignored):
            # accepted for drop-in CLI parity; journal offsets replace
            # ZooKeeper coordination and consumer-group bookkeeping
            print(f"[serve] --{ignored} accepted and ignored", file=sys.stderr)
    # retrieval-plane knobs ride the environment (the index reads them at
    # construction, including inside rebuilds); CLI flags win over an
    # inherited env so one launcher line fully describes the worker
    for flag, env in (("topkTier", "TPUMS_TOPK_TIER"),
                      ("topkSharded", "TPUMS_TOPK_SHARDED"),
                      ("annNlist", "TPUMS_ANN_NLIST"),
                      ("annNprobe", "TPUMS_ANN_NPROBE")):
        if params.has(flag):
            os.environ[env] = str(params.get(flag))
    journal = Journal(_resolve_journal_dir(params), params.get_required("topic"))
    backend = make_backend(
        params.get("stateBackend", "memory"), params.get("checkpointDataUri")
    )
    job = ServingJob(
        journal,
        state_name,
        parse_fn,
        backend,
        n_shards=params.get_int("shards", 8),
        checkpoint_interval_ms=params.get_int("checkPointInterval", 60_000),
        host=params.get("host", "0.0.0.0"),
        port=params.get_int("port", 6123),
        job_id=params.get("jobId"),
        native_server=params.get_bool("nativeServer", False),
        start_from=params.get("startFrom", "earliest"),
        ingest_mode=params.get("ingestMode"),
        snapshots=(
            params.get_bool("snapshots") if params.has("snapshots") else None
        ),
        snapshot_min_bytes=params.get_int("snapshotMinBytes"),
        compact=params.get_bool("compact") if params.has("compact") else None,
        table=params.get("table"),  # dict (default) | arena; TPUMS_TABLE env
    )
    print(
        f"[serve] {state_name} serving topic '{journal.topic}' on port "
        f"{job.port}, jobId={job.job_id}"
    )
    return job.start()


def als_main(argv=None) -> None:
    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    _run_consumer_cli(params, ALS_STATE, parse_als_record).wait()


def svm_main(argv=None) -> None:
    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    _run_consumer_cli(params, SVM_STATE, parse_svm_record).wait()
