"""Sharded model table — the TPU-native equivalent of the reference's
queryable keyed ``ValueState`` (``keyBy(0).asQueryableState("ALS_MODEL", ...)``,
``ALSKafkaConsumer.java:85-92``).

Keys are strings (``"<id>-U"``, ``"<id>-I"``, feature ids, bucket ids);
values are the row payloads.  Rows are hash-partitioned into shards exactly
like Flink routes keys to operator subtasks; last-writer-wins per key.

Snapshots are plain ``key\\tvalue`` TSV per shard plus a JSON manifest with
the journal offset — the unit of the checkpoint/restore cycle
(``enableCheckpointing`` + state backend selection,
``ALSKafkaConsumer.java:44-65``).  The TSV format is deliberately
language-neutral so the C++ persistent backend can share it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

MANIFEST = "MANIFEST.json"


def _fnv1a(s: str) -> int:
    """Stable 32-bit FNV-1a — shard routing must not depend on Python's
    per-process hash randomization."""
    h = 0x811C9DC5
    for ch in s.encode("utf-8"):
        h ^= ch
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def _fnv1a_batch(keys) -> "np.ndarray":
    """Vectorized FNV-1a over a batch of keys (uint32 wrap = the & mask).

    Byte-identical to ``_fnv1a`` per key; the per-character loop runs over
    the LONGEST key only, with shorter keys masked out — ~10x less Python
    bytecode per key at ingest batch sizes.  Returns uint32 hashes."""
    bs = [k.encode("utf-8") for k in keys]
    n = len(bs)
    L = max((len(b) for b in bs), default=0)
    if L == 0:
        return np.full(n, 0x811C9DC5, np.uint32)
    if L > 256:
        # one oversized key must only cost itself, not an (n, L) buffer
        # and an L-deep masked loop for the whole batch
        return np.fromiter(
            (_fnv1a(k) for k in keys), np.uint32, n
        )
    buf = np.zeros((n, L), np.uint8)
    lens = np.fromiter((len(b) for b in bs), np.int64, n)
    flat = np.frombuffer(b"".join(bs), np.uint8)
    # scatter each key's bytes into its padded row
    row = np.repeat(np.arange(n), lens)
    col = np.arange(flat.size) - np.repeat(np.cumsum(lens) - lens, lens)
    buf[row, col] = flat
    h = np.full(n, 0x811C9DC5, np.uint32)
    prime = np.uint32(0x01000193)
    for j in range(L):
        active = j < lens
        hx = (h ^ buf[:, j]) * prime
        h = np.where(active, hx, h)
    return h


class ModelTable:
    def __init__(self, n_shards: int = 8):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        self._shards: List[Dict[str, str]] = [dict() for _ in range(n_shards)]
        self._lock = threading.RLock()
        self.puts = 0  # ingest counter (observability)
        # bumped on EVERY mutation (put, put_many, restore) — derived
        # read-side caches (e.g. the DOT merged range index) key on it
        self.version = 0
        self._listeners: List = []  # change listeners (e.g. the top-k index)
        # parallel list: optional batched callbacks, one entry per listener
        # (None = fall back to per-key fn inside put_many)
        self._batch_listeners: List = []

    def add_change_listener(self, fn, batch_fn=None) -> None:
        """Register fn(key) to be called on every put.  Callbacks run on
        the writer thread under the table lock — keep them O(1) (the top-k
        index just records the key in its dirty set).

        ``batch_fn(keys)``, when given, replaces the per-key calls for
        batched ingest (``put_many``/``put_many_columns``): ONE callback
        per chunk instead of one per row, so a listener can take its own
        lock once per chunk (the top-k index's dirty set)."""
        with self._lock:
            self._listeners.append(fn)
            self._batch_listeners.append(batch_fn)

    def shard_of(self, key: str) -> int:
        return _fnv1a(key) % self.n_shards

    def put(self, key: str, value: str) -> None:
        with self._lock:
            self._shards[self.shard_of(key)][key] = value
            self.puts += 1
            self.version += 1
            for fn in self._listeners:
                fn(key)

    def put_many(self, pairs) -> None:
        """Batched ingest: one lock acquisition and one vectorized hash
        pass per batch — the ingest hot path (at 1M-row replays the
        per-key Python FNV loop was the measured pipeline bottleneck)."""
        pairs = list(pairs)
        if not pairs:
            return
        self.put_many_columns([k for k, _ in pairs], [v for _, v in pairs])

    def put_many_columns(self, keys, values, hashes=None) -> None:
        """Columnar batched ingest: keys/values as parallel sequences.

        The per-row Python work of ``put_many`` (tuple unpack, per-key
        dict insert bytecode, per-key listener call) is replaced by a
        stable shard-sort and ONE ``dict.update`` per touched shard, plus
        one batched listener notification per chunk — the whole row loop
        runs in C.  Last-writer-wins order is preserved: the sort is
        stable, so within a shard duplicates keep input order.

        ``hashes``, when given, is the per-key uint32 FNV-1a array (the
        columnar chunk parser computes it from the raw bytes, skipping
        the per-key encode of ``_fnv1a_batch``); it must match
        ``_fnv1a(key)`` per key."""
        n = len(keys)
        if n == 0:
            return
        if not isinstance(keys, list):
            keys = list(keys)
        if n < 32:
            # tiny batch: the argsort/array machinery costs more than the
            # plain loop it replaces
            shard_ids = (
                _fnv1a_batch(keys) if hashes is None else hashes
            ) % self.n_shards
            with self._lock:
                for key, value, sid in zip(keys, values, shard_ids):
                    self._shards[sid][key] = value
                self.puts += n
                self.version += 1
                self._notify_locked(keys)
            return
        shard_ids = (
            _fnv1a_batch(keys) if hashes is None else hashes
        ) % self.n_shards
        order = np.argsort(shard_ids, kind="stable")
        ks = np.asarray(keys, dtype=object)[order]
        vs = np.asarray(values, dtype=object)[order]
        bounds = np.searchsorted(
            shard_ids[order], np.arange(self.n_shards + 1)
        )
        with self._lock:
            for sid in range(self.n_shards):
                s, e = bounds[sid], bounds[sid + 1]
                if s < e:
                    self._shards[sid].update(
                        zip(ks[s:e].tolist(), vs[s:e].tolist())
                    )
            self.puts += n
            self.version += 1
            self._notify_locked(keys)

    def _notify_locked(self, keys) -> None:
        for fn, batch_fn in zip(self._listeners, self._batch_listeners):
            if batch_fn is not None:
                batch_fn(keys)
            else:
                for key in keys:
                    fn(key)

    def get(self, key: str) -> Optional[str]:
        return self._shards[self.shard_of(key)].get(key)

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def items(self) -> Iterator[Tuple[str, str]]:
        with self._lock:
            snap = [dict(s) for s in self._shards]
        for s in snap:
            yield from s.items()

    # -- snapshot / restore -------------------------------------------------

    def snapshot(self, checkpoint_dir: str, offset: int) -> str:
        """Write a consistent (table, journal offset) snapshot; returns the
        checkpoint path.  Atomic via tmp-dir + rename; a ``latest`` pointer
        file names the newest complete checkpoint."""
        with self._lock:
            shards_copy = [dict(s) for s in self._shards]
        chk_id = f"chk-{int(time.time() * 1000)}"
        tmp = os.path.join(checkpoint_dir, f".tmp-{chk_id}")
        os.makedirs(tmp, exist_ok=True)
        for idx, shard in enumerate(shards_copy):
            with open(os.path.join(tmp, f"shard-{idx}.tsv"), "w") as f:
                for k, v in shard.items():
                    f.write(f"{k}\t{v}\n")
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(
                {"offset": offset, "n_shards": self.n_shards, "ts": time.time()}, f
            )
        final = os.path.join(checkpoint_dir, chk_id)
        os.rename(tmp, final)
        with open(os.path.join(checkpoint_dir, "latest.tmp"), "w") as f:
            f.write(chk_id)
        os.replace(
            os.path.join(checkpoint_dir, "latest.tmp"),
            os.path.join(checkpoint_dir, "latest"),
        )
        self._prune(checkpoint_dir, keep=2)
        return final

    @staticmethod
    def _prune(checkpoint_dir: str, keep: int) -> None:
        chks = sorted(
            d for d in os.listdir(checkpoint_dir) if d.startswith("chk-")
        )
        for old in chks[:-keep]:
            import shutil

            shutil.rmtree(os.path.join(checkpoint_dir, old), ignore_errors=True)

    def restore(self, checkpoint_dir: str) -> Optional[int]:
        """Load the latest complete checkpoint; returns the journal offset,
        or None if no checkpoint exists."""
        latest_file = os.path.join(checkpoint_dir, "latest")
        if not os.path.exists(latest_file):
            return None
        with open(latest_file) as f:
            chk_id = f.read().strip()
        chk = os.path.join(checkpoint_dir, chk_id)
        with open(os.path.join(chk, MANIFEST)) as f:
            manifest = json.load(f)
        if manifest["n_shards"] != self.n_shards:
            raise ValueError(
                f"checkpoint has {manifest['n_shards']} shards, table has "
                f"{self.n_shards}"
            )
        with self._lock:
            for idx in range(self.n_shards):
                shard: Dict[str, str] = {}
                path = os.path.join(chk, f"shard-{idx}.tsv")
                if os.path.exists(path):
                    with open(path) as f:
                        for line in f:
                            line = line.rstrip("\n")
                            if not line:
                                continue
                            k, _, v = line.partition("\t")
                            shard[k] = v
                self._shards[idx] = shard
            self.version += 1
        return int(manifest["offset"])
