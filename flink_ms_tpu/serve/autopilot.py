"""Continuous-training autopilot: the unattended train->serve->update->
retrain flywheel.

The reference closes its loop by hand — a human runs ``ALSImpl``, pushes
factors through the Kafka producer, and the consumer picks them up
(PAPER.md modules 1/3/5).  This controller removes the human: it ties
five existing subsystems (update-plane journals, snapshot+tail reads,
warm-started ALS, the held-out evaluator, blue/green rollout, the watch
plane's drift canary) into one closed loop that runs forever::

    idle -> windowing -> training -> evaluating -> rolling-out -> watching
      ^        |            |            |              |            |
      +--------+------------+------------+--------------+------------+

Per tick (``TPUMS_AUTOPILOT_INTERVAL_S``):

1. **watching** — if the PR 12 ``model_drift`` alert is firing, or the
   live canary MSE (``tpums_model_live_mse``) has regressed past the
   rollout-time probe by ``drift_factor``, drive
   ``RolloutController.rollback()`` — one command, zero failed queries,
   previous answers restored.  Disarmed after a rollback until the next
   rollout so one incident cannot ping-pong the fleet.
2. **windowing** — tail NEW ratings out of the update plane's
   per-partition input journals (``<topic>.upd<p>``, the PR 7
   snapshot+tail machinery: offsets persist across restarts, truncated
   offsets reset losslessly through the compacted prefix) into the
   accumulated last-write-wins training set; when at least
   ``min_window`` new ratings arrived, seal a VERSIONED window file.
3. **training** — ALS retrain **warm-started from the current serving
   factors** (``ops/als.py warm_start_factors`` aligns the served model
   onto the window's id space; novel ids fall back to the cold seed
   draw) so iterations-to-converge drops on incremental data.
4. **evaluating** — candidate vs incumbent on the window's rolling
   held-out slice (``eval/mse.rolling_holdout_split``: seeded,
   user-stratified) through ``eval/mse.compute_mse``'s exact reference
   grouping — the SAME statistic the live canary publishes.
5. **rolling-out** — when the candidate wins by at least
   ``improvement``, ``RolloutController.rollout()`` with a row-count
   floor and a held-out MSE probe gate; the rollout-time probe MSE is
   persisted as the drift baseline for step 1.

Crash safety: a single JSON state record (``autopilot_state.json``,
atomic tmp+rename) holds the partition offsets, window/model versions and
the drift baseline; the controller runs under its OWN registry lease
scope (``<group>#autopilot`` — distinct from the group lease
``rollout()`` itself takes, so the two protocols never self-deadlock) and
a SIGKILLed holder's lease is stolen by the next process, which resumes
from the persisted record.  Serving never depends on the autopilot being
alive — workers outlive it by construction.

Metrics: ``tpums_autopilot_*`` counters/gauges through the process
registry, surfaced fleet-wide by ``obs/scrape.fleet_signals``.

CLI::

    python -m flink_ms_tpu.serve.autopilot --group als \\
        --ratingsDir /data/bus --workDir /data/autopilot \\
        [--topic models] [--bootstrap /data/v0 --shards 2] \\
        [--duration 60 | --once] [--interval 2] [--minWindow 200]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import formats as F
from ..obs.metrics import get_registry
from ..obs.tracing import event
from . import registry
from .elastic import ControllerBusy, ScaleError
from .journal import Journal
from .rollout import RolloutController, RolloutError, VerificationError
from .update_plane import default_partitions, input_topic

__all__ = ["AutopilotController", "PHASES", "autopilot_scope", "main"]

# the state machine, in gauge order (tpums_autopilot_phase publishes the
# index so a scrape can plot transitions)
PHASES = ("idle", "windowing", "training", "evaluating", "rolling-out",
          "watching", "standby")
_PHASE_LEVEL = {name: i for i, name in enumerate(PHASES)}

STATE_FILE = "autopilot_state.json"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def autopilot_scope(group: str) -> str:
    """The autopilot's OWN controller-lease scope.  Distinct from the
    group scope because ``ScaleController.scale_to`` (which ``rollout()``
    drives) takes the group lease itself — an autopilot leasing the group
    would deadlock against its own rollout."""
    return f"{group}#autopilot"


def _read_journal_lines(journal_dir: str, topic: str) -> List[str]:
    """Every line of a model journal (snapshot-agnostic full read; resets
    through truncation, so a compacted journal reads its folded prefix)."""
    j = Journal(journal_dir, topic)
    out: List[str] = []
    off = j.start_offset()
    while True:
        lines, off2 = j.read_from(off, on_truncated="reset")
        if not lines and off2 == off:
            return out
        out.extend(lines)
        off = off2


class AutopilotController:
    """The unattended retrain loop for one serving group (see module
    docstring).  Use ``tick()`` synchronously (tests, ``--once``) or
    ``start()``/``stop()`` for the background loop."""

    def __init__(
        self,
        group: str,
        ratings_dir: str,
        work_dir: str,
        *,
        topic: str = "models",
        tenant: Optional[str] = None,
        rollout: Optional[RolloutController] = None,
        rollout_kw: Optional[dict] = None,
        interval_s: Optional[float] = None,
        min_window: Optional[int] = None,
        improvement: Optional[float] = None,
        holdout_fraction: Optional[float] = None,
        iterations: Optional[int] = None,
        num_factors: Optional[int] = None,
        lambda_: float = 0.1,
        drift_source: Optional[str] = None,
        drift_factor: Optional[float] = None,
        drift_rule: str = "model_drift",
        partitions: Optional[int] = None,
        max_probe: int = 256,
        seed: int = 42,
        lease_ttl_s: Optional[float] = None,
        live_mse=None,
    ):
        self.ratings_dir = ratings_dir
        self.topic = topic
        self.work_dir = os.path.abspath(work_dir)
        os.makedirs(os.path.join(self.work_dir, "windows"), exist_ok=True)
        os.makedirs(os.path.join(self.work_dir, "models"), exist_ok=True)
        self.rollout_ctl = rollout if rollout is not None else \
            RolloutController(group, tenant=tenant, **(rollout_kw or {}))
        self.group = self.rollout_ctl.group  # tenant-qualified
        self.interval_s = (
            _env_float("TPUMS_AUTOPILOT_INTERVAL_S", 2.0)
            if interval_s is None else float(interval_s))
        self.min_window = (
            _env_int("TPUMS_AUTOPILOT_MIN_WINDOW", 100)
            if min_window is None else int(min_window))
        self.improvement = (
            _env_float("TPUMS_AUTOPILOT_IMPROVEMENT", 0.0)
            if improvement is None else float(improvement))
        self.holdout_fraction = (
            _env_float("TPUMS_AUTOPILOT_HOLDOUT", 0.2)
            if holdout_fraction is None else float(holdout_fraction))
        self.iterations = (
            _env_int("TPUMS_AUTOPILOT_ITERS", 4)
            if iterations is None else int(iterations))
        self.num_factors = (
            _env_int("TPUMS_AUTOPILOT_FACTORS", 8)
            if num_factors is None else int(num_factors))
        self.lambda_ = lambda_
        self.drift_source = (
            os.environ.get("TPUMS_AUTOPILOT_DRIFT_SOURCE", "both")
            if drift_source is None else drift_source)
        if self.drift_source not in ("alert", "gauge", "both", "off"):
            raise ValueError(
                f"drift_source must be alert|gauge|both|off, "
                f"got {self.drift_source!r}")
        self.drift_factor = (
            _env_float("TPUMS_AUTOPILOT_DRIFT_FACTOR", 1.5)
            if drift_factor is None else float(drift_factor))
        self.drift_rule = drift_rule
        self.partitions = partitions or default_partitions()
        self.max_probe = int(max_probe)
        self.seed = int(seed)
        self.lease_ttl_s = lease_ttl_s
        self._live_mse_fn = live_mse
        self._scope = autopilot_scope(self.group)
        self._token: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0
        self.last_error: Optional[str] = None
        # accumulated LWW training set: (user, item) -> rating
        self._acc: Dict[Tuple[int, int], float] = {}
        self.state = self._load_state()
        self._restore_window()

    # -- persisted state ---------------------------------------------------

    @property
    def state_path(self) -> str:
        return os.path.join(self.work_dir, STATE_FILE)

    def _load_state(self) -> dict:
        try:
            with open(self.state_path) as f:
                rec = json.load(f)
            if rec.get("kind") == "autopilot":
                return rec
        except (OSError, ValueError):
            pass
        return {
            "kind": "autopilot", "group": self.group, "phase": "idle",
            "offsets": {}, "window_version": 0, "window_rows": 0,
            "trained_version": 0, "model_seq": 0,
            "rollout_probe_mse": None, "incumbent_model_id": None,
            "drift_armed": False, "heldout_mse": None,
            "retrains": 0, "rollouts": 0, "rollbacks": 0,
            "wins": 0, "losses": 0, "updated_at": 0.0,
        }

    def _save_state(self) -> None:
        self.state["updated_at"] = time.time()
        tmp = f"{self.state_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f, indent=1)
        os.replace(tmp, self.state_path)

    def _window_path(self, version: int) -> str:
        return os.path.join(self.work_dir, "windows",
                            f"window-v{version:06d}.tsv")

    def _restore_window(self) -> None:
        """Rebuild the in-memory LWW set from the last sealed window file
        (crash/restart path).  Offsets in the state record point at the
        first UNWINDOWED rating, so the tail picks up exactly after it."""
        v = int(self.state.get("window_version", 0))
        if v <= 0:
            return
        path = self._window_path(v)
        try:
            users, items, ratings = F.read_ratings(
                path, field_delimiter="\t", ignore_first_line=True)
        except OSError:
            return
        for u, i, r in zip(users, items, ratings):
            self._acc[(int(u), int(i))] = float(r)

    # -- lease -------------------------------------------------------------

    def _ensure_lease(self) -> bool:
        if self._token is not None:
            if registry.refresh_controller_lease(self._scope, self._token):
                return True
            self._token = None
        self._token = registry.acquire_controller_lease(
            self._scope, ttl_s=self.lease_ttl_s)
        if self._token is not None:
            event("autopilot_lease_acquired", group=self.group)
            return True
        return False

    def release_lease(self) -> None:
        if self._token is not None:
            registry.release_controller_lease(self._scope, self._token)
            self._token = None

    # -- metrics / phase ---------------------------------------------------

    def _set_phase(self, phase: str) -> None:
        self.state["phase"] = phase
        get_registry().gauge("tpums_autopilot_phase").set(
            _PHASE_LEVEL[phase])
        # the chaos harness targets its SIGKILLs by polling the persisted
        # phase, so every transition must reach disk, not just the gauge
        self._save_state()

    def _publish_gauges(self) -> None:
        reg = get_registry()
        reg.gauge("tpums_autopilot_window_rows").set(len(self._acc))
        if self.state.get("heldout_mse") is not None:
            reg.gauge("tpums_autopilot_heldout_mse").set(
                self.state["heldout_mse"])
        reg.gauge("tpums_autopilot_lease_held").set(
            1.0 if self._token else 0.0)

    def _count(self, name: str, key: str) -> None:
        self.state[key] = int(self.state.get(key, 0)) + 1
        get_registry().counter(f"tpums_autopilot_{name}_total").inc()

    # -- windowing ---------------------------------------------------------

    def _tail_ratings(self) -> int:
        """Drain every partition's input journal from the persisted
        offsets into the LWW set -> number of new rating rows."""
        offsets = self.state.setdefault("offsets", {})
        new_rows = 0
        for p in range(self.partitions):
            j = Journal(self.ratings_dir, input_topic(self.topic, p))
            off = int(offsets.get(str(p), j.start_offset()))
            while True:
                lines, off2 = j.read_from(off, on_truncated="reset")
                if not lines and off2 == off:
                    break
                for line in lines:
                    try:
                        _seq, u, i, r = line.split("\t")
                        self._acc[(int(u), int(i))] = float(r)
                        new_rows += 1
                    except ValueError:
                        continue  # torn/foreign line: not a rating
                off = off2
            offsets[str(p)] = off
        return new_rows

    def _seal_window(self) -> Tuple[int, np.ndarray, np.ndarray,
                                    np.ndarray]:
        """Materialize the accumulated set as the next versioned training
        window (atomic file publish, then the state record advances)."""
        version = int(self.state["window_version"]) + 1
        keys = sorted(self._acc)
        users = np.asarray([k[0] for k in keys], dtype=np.int64)
        items = np.asarray([k[1] for k in keys], dtype=np.int64)
        ratings = np.asarray([self._acc[k] for k in keys],
                             dtype=np.float64)
        path = self._window_path(version)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write("user\titem\trating\n")
            for u, i, r in zip(users, items, ratings):
                f.write(f"{int(u)}\t{int(i)}\t{float(r)!r}\n")
        os.replace(tmp, path)
        prev = self._window_path(version - 1)
        if os.path.exists(prev):
            os.unlink(prev)  # the newest window subsumes it (LWW set)
        self.state["window_version"] = version
        self.state["window_rows"] = len(keys)
        self._save_state()
        self._count("windows", "windows")
        return version, users, items, ratings

    # -- training ----------------------------------------------------------

    def _incumbent_tables(self) -> Tuple[Dict[int, np.ndarray],
                                         Dict[int, np.ndarray]]:
        """The served model's factors keyed by raw numeric id (warm-start
        source + incumbent side of the evaluation)."""
        topo = self.rollout_ctl.current() or {}
        model = topo.get("model") or {}
        jd, tp = model.get("journal_dir"), model.get("topic")
        users: Dict[int, np.ndarray] = {}
        items: Dict[int, np.ndarray] = {}
        if not jd or not tp or not os.path.isdir(jd):
            return users, items
        try:
            lines = _read_journal_lines(jd, tp)
        except OSError:
            return users, items
        for line in lines:
            try:
                id_, typ, vec = F.parse_als_row(line)
                id_n = int(id_)
            except ValueError:
                continue  # MEAN row / foreign line
            (users if typ == "U" else items)[id_n] = vec
        return users, items

    def _train(self, version: int, users: np.ndarray, items: np.ndarray,
               ratings: np.ndarray) -> dict:
        """Warm-started retrain on the window's train slice -> candidate
        ``{model_id, journal_dir, tables, heldout, warm}``."""
        from ..eval.mse import rolling_holdout_split
        from ..ops.als import ALSConfig, als_fit, warm_start_factors
        from ..parallel.mesh import honor_platform_env, make_mesh

        honor_platform_env()  # JAX_PLATFORMS pin must precede device work

        train_idx, hold_idx = rolling_holdout_split(
            users, items, ratings, fraction=self.holdout_fraction,
            seed=self.seed + version)
        tr_u, tr_i, tr_r = users[train_idx], items[train_idx], \
            ratings[train_idx]
        prev_u, prev_i = self._incumbent_tables()
        k = self.num_factors
        kw = {}
        warm = bool(prev_u and prev_i)
        if warm:
            uf0, itf0 = warm_start_factors(
                np.unique(tr_u), np.unique(tr_i), prev_u, prev_i, k,
                seed=self.seed)
            kw = {"init_user_factors": uf0, "init_item_factors": itf0}
        t0 = time.perf_counter()
        config = ALSConfig(num_factors=k, iterations=self.iterations,
                           lambda_=self.lambda_, seed=self.seed)
        model = als_fit(tr_u, tr_i, tr_r, config, make_mesh(1), **kw)
        train_s = time.perf_counter() - t0
        get_registry().gauge("tpums_autopilot_last_retrain_s").set(train_s)
        self._count("retrains", "retrains")
        # NB: trained_version is NOT advanced here — the window only
        # counts as trained once the rollout decision concluded (tick()),
        # so a SIGKILL mid-retrain OR mid-rollout makes the next lease
        # holder redo the whole train->evaluate->rollout unit from the
        # sealed window (model_seq IS durable: candidate dirs never
        # collide across crashes)
        seq = int(self.state["model_seq"]) + 1
        model_id = f"auto-v{seq:06d}"
        final = os.path.join(self.work_dir, "models", model_id)
        tmp = f"{final}.tmp-{os.getpid()}"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        j = Journal(tmp, self.topic)
        j.append(
            [F.format_als_row(int(uid), "U", vec) for uid, vec
             in zip(model.user_ids, model.user_factors)]
            + [F.format_als_row(int(iid), "I", vec) for iid, vec
               in zip(model.item_ids, model.item_factors)])
        j.sync()
        if os.path.isdir(final):
            shutil.rmtree(final)  # a crashed cycle's leftover
        os.rename(tmp, final)
        self.state["model_seq"] = seq
        self._save_state()
        event("autopilot_retrain", group=self.group, model_id=model_id,
              window_version=version, rows=len(tr_r),
              warm_start=warm, train_s=round(train_s, 3))
        tables = {f"{int(u)}-U": vec for u, vec
                  in zip(model.user_ids, model.user_factors)}
        tables.update({f"{int(i)}-I": vec for i, vec
                       in zip(model.item_ids, model.item_factors)})
        return {
            "model_id": model_id, "journal_dir": final, "tables": tables,
            "rows": len(model.user_ids) + len(model.item_ids),
            "heldout": (users[hold_idx], items[hold_idx],
                        ratings[hold_idx]),
            "warm": warm, "train_s": train_s,
        }

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _table_mse(table: Dict[str, np.ndarray], users, items, ratings
                   ) -> Tuple[Optional[float], int]:
        from ..eval.mse import compute_mse

        def lookup(key):
            return table.get(key)

        def lookup_many(keys):
            return [table.get(k) for k in keys]

        mse, n_scored, _ = compute_mse(users, items, ratings, lookup,
                                       lookup_many=lookup_many)
        return mse, n_scored

    def _evaluate(self, candidate: dict) -> dict:
        """Candidate vs incumbent on the held-out slice — exact
        ``compute_mse`` grouping on both sides, same slice, so the
        comparison is one statistic, not two."""
        h_u, h_i, h_r = candidate["heldout"]
        cand_mse, cand_scored = self._table_mse(
            candidate["tables"], h_u, h_i, h_r)
        prev_u, prev_i = self._incumbent_tables()
        inc_table = {f"{u}-U": v for u, v in prev_u.items()}
        inc_table.update({f"{i}-I": v for i, v in prev_i.items()})
        inc_mse, inc_scored = (self._table_mse(inc_table, h_u, h_i, h_r)
                               if inc_table else (None, 0))
        cand_mse = None if cand_mse is None else float(cand_mse)
        inc_mse = None if inc_mse is None else float(inc_mse)
        win = bool(cand_mse is not None and (
            inc_mse is None
            or cand_mse <= inc_mse * (1.0 - self.improvement)))
        if cand_mse is not None:
            self.state["heldout_mse"] = float(cand_mse)
            get_registry().gauge("tpums_autopilot_heldout_mse").set(
                float(cand_mse))
        self._count("wins" if win else "losses",
                    "wins" if win else "losses")
        self._save_state()
        return {"candidate_mse": cand_mse, "incumbent_mse": inc_mse,
                "candidate_scored": cand_scored,
                "incumbent_scored": inc_scored, "win": win}

    # -- rollout / rollback ------------------------------------------------

    def _probe_slice(self, heldout) -> dict:
        h_u, h_i, h_r = heldout
        if len(h_r) > self.max_probe:
            idx = np.linspace(0, len(h_r) - 1, self.max_probe).astype(int)
            h_u, h_i, h_r = h_u[idx], h_i[idx], h_r[idx]
        return {"users": h_u, "items": h_i, "ratings": h_r}

    def _roll_out(self, candidate: dict, evaluation: dict) -> dict:
        probe = self._probe_slice(candidate["heldout"])
        cand_mse = evaluation["candidate_mse"]
        # gate: the warming generation must reproduce the offline score
        # (loose factor: the probe subsamples the slice, and a row-floor
        # failure should abort loudly, not a sampling wobble)
        probe["max_mse"] = max(cand_mse * 2.0, cand_mse + 0.5)
        record = self.rollout_ctl.rollout(
            candidate["journal_dir"], self.topic,
            model_id=candidate["model_id"],
            verify_min_rows=candidate["rows"], probe=probe)
        self._count("rollouts", "rollouts")
        self.state["rollout_probe_mse"] = float(cand_mse)
        self.state["incumbent_model_id"] = candidate["model_id"]
        self.state["drift_armed"] = True
        self._save_state()
        event("autopilot_rollout", group=self.group,
              model_id=candidate["model_id"], gen=record.get("gen"),
              heldout_mse=round(float(cand_mse), 6))
        return record

    def _live_mse(self) -> Optional[float]:
        if self._live_mse_fn is not None:
            try:
                v = self._live_mse_fn()
            except Exception:
                return None
            return None if v is None else float(v)
        v = get_registry().gauge("tpums_model_live_mse").value
        return v if v > 0.0 else None  # 0 = the canary never scored

    def _drift_fired(self) -> Optional[str]:
        if self.drift_source == "off" or not self.state.get("drift_armed"):
            return None
        if self.drift_source in ("alert", "both"):
            rec = registry.resolve_alerts()
            for alert in (rec or {}).get("alerts", ()):
                if alert.get("rule") == self.drift_rule:
                    return f"alert:{self.drift_rule}"
        if self.drift_source in ("gauge", "both"):
            baseline = self.state.get("rollout_probe_mse")
            live = self._live_mse()
            if baseline is not None and live is not None and \
                    live > baseline * self.drift_factor:
                return (f"live_mse {live:.4f} > "
                        f"{self.drift_factor:g}x probe {baseline:.4f}")
        return None

    def _roll_back(self, reason: str) -> Optional[dict]:
        try:
            record = self.rollout_ctl.rollback()
        except (RolloutError, VerificationError) as e:
            self.last_error = f"rollback: {e}"
            return None
        self._count("rollbacks", "rollbacks")
        # disarm until the next rollout: the alert needs a few canary
        # rounds to resolve, and re-rolling back during them would
        # ping-pong between the only two models in history
        self.state["drift_armed"] = False
        self.state["rollout_probe_mse"] = None
        self.state["incumbent_model_id"] = (
            record.get("model") or {}).get("model_id")
        self._save_state()
        event("autopilot_rollback", group=self.group, reason=reason,
              restored=self.state["incumbent_model_id"],
              gen=record.get("gen"))
        return record

    # -- one tick ----------------------------------------------------------

    def tick(self) -> dict:
        """One pass of the state machine -> what happened this tick."""
        out: dict = {"ts": time.time(), "group": self.group}
        self.ticks += 1
        if not self._ensure_lease():
            out["state"] = "standby"
            get_registry().gauge("tpums_autopilot_phase").set(
                _PHASE_LEVEL["standby"])
            self._publish_gauges()
            return out
        try:
            self._set_phase("watching")
            reason = self._drift_fired()
            if reason is not None:
                out["drift"] = reason
                rec = self._roll_back(reason)
                out["rollback"] = rec.get("gen") if rec else None
                self._set_phase("idle")
                return out
            self._set_phase("windowing")
            new_rows = self._tail_ratings()
            out["new_ratings"] = new_rows
            pending = int(self.state["window_version"]) > \
                int(self.state["trained_version"])
            if new_rows < self.min_window and not pending:
                # not enough new signal: persist the offsets we advanced
                # past non-rating lines, but don't seal a window
                self._set_phase("idle")
                return out
            if pending:
                # a previous holder sealed this window then died before
                # training: resume it instead of sealing another
                version = int(self.state["window_version"])
                users, items, ratings = F.read_ratings(
                    self._window_path(version), field_delimiter="\t",
                    ignore_first_line=True)
                out["resumed_window"] = version
            else:
                version, users, items, ratings = self._seal_window()
            out["window_version"] = version
            out["window_rows"] = len(ratings)
            self._set_phase("training")
            candidate = self._train(version, users, items, ratings)
            out["model_id"] = candidate["model_id"]
            out["warm_start"] = candidate["warm"]
            out["train_s"] = round(candidate["train_s"], 3)
            self._set_phase("evaluating")
            evaluation = self._evaluate(candidate)
            out.update({k: evaluation[k] for k in
                        ("candidate_mse", "incumbent_mse", "win")})
            if evaluation["win"]:
                self._set_phase("rolling-out")
                try:
                    record = self._roll_out(candidate, evaluation)
                    out["rollout_gen"] = record.get("gen")
                except (RolloutError, VerificationError, ControllerBusy,
                        ScaleError, registry.TopologyConflict) as e:
                    # refused candidates never reach traffic; the active
                    # generation kept serving (scale_to's abort contract)
                    self.last_error = f"rollout: {e}"
                    out["rollout_error"] = str(e)
            # the train->evaluate->rollout unit concluded (rolled out,
            # lost, or cleanly refused): the window is consumed
            self.state["trained_version"] = version
            self._set_phase("watching")
            return out
        finally:
            self._publish_gauges()

    # -- lifecycle ---------------------------------------------------------

    def run(self, duration_s: Optional[float] = None) -> dict:
        """Tick on the cadence until ``duration_s`` (or stop()) — the CLI
        foreground loop."""
        t_end = None if duration_s is None else time.time() + duration_s
        while not self._stop.is_set():
            t0 = time.time()
            if t_end is not None and t0 >= t_end:
                break
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self.last_error = f"{type(e).__name__}: {e}"
                event("autopilot_tick_error", group=self.group,
                      error=self.last_error)
            self._stop.wait(max(self.interval_s - (time.time() - t0),
                                0.01))
        return self.summary()

    def start(self) -> "AutopilotController":
        if self._thread is not None:
            raise RuntimeError("autopilot already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, daemon=True, name="tpums-autopilot")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(30.0, 3 * self.interval_s))
            self._thread = None
        self.release_lease()

    def __enter__(self) -> "AutopilotController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def summary(self) -> dict:
        """The artifact section bench/chaos runs record."""
        return {
            "group": self.group, "ticks": self.ticks,
            "phase": self.state.get("phase"),
            "window_version": self.state.get("window_version"),
            "window_rows": len(self._acc),
            "retrains": self.state.get("retrains", 0),
            "rollouts": self.state.get("rollouts", 0),
            "rollbacks": self.state.get("rollbacks", 0),
            "wins": self.state.get("wins", 0),
            "losses": self.state.get("losses", 0),
            "heldout_mse": self.state.get("heldout_mse"),
            "rollout_probe_mse": self.state.get("rollout_probe_mse"),
            "incumbent_model_id": self.state.get("incumbent_model_id"),
            "last_error": self.last_error,
        }


def main(argv=None) -> int:
    from ..core.params import Params

    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    if not params.has("group") or not params.has("ratingsDir") \
            or not params.has("workDir"):
        print(__doc__)
        return 2
    pilot = AutopilotController(
        params.get_required("group"),
        params.get_required("ratingsDir"),
        params.get_required("workDir"),
        topic=params.get("topic", "models"),
        tenant=params.get("tenant", None),
        interval_s=(float(params.get("interval"))
                    if params.has("interval") else None),
        min_window=(params.get_int("minWindow", 0) or None),
        iterations=(params.get_int("iterations", 0) or None),
        num_factors=(params.get_int("numFactors", 0) or None),
        rollout_kw={
            "port_dir": params.get("portDir", None),
            "replication": params.get_int("replication", 1),
            "ready_timeout_s": float(params.get("readyTimeoutS", "180")),
        },
    )
    # bootstrap: a fresh group with no topology gets generation 1 from
    # the seed model so the flywheel has an incumbent to improve on.
    # Bare --bootstrap (no journal dir) is also legal: the first tick
    # cold-trains gen 1 from the accumulated window itself — there is no
    # incumbent, so the candidate wins by definition and rolls out.
    if params.has("bootstrap") and pilot.rollout_ctl.current() is None:
        seed_dir = params.get("bootstrap", None)
        if seed_dir:
            record = pilot.rollout_ctl.rollout(
                seed_dir,
                params.get("topic", "models"),
                model_id=params.get("bootstrapModelId", "seed"),
                shards=params.get_int("shards", 1))
            print(json.dumps({"bootstrap_gen": record["gen"]}), flush=True)
    try:
        if params.has("once"):
            result = pilot.tick()
            print(json.dumps(result, indent=1, default=str))
        else:
            duration = (float(params.get("duration"))
                        if params.has("duration") else None)
            pilot.run(duration_s=duration)
            print(json.dumps(pilot.summary(), indent=1, default=str))
    except KeyboardInterrupt:
        pass
    finally:
        pilot.release_lease()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
