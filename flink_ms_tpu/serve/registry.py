"""Job location registry — the jobId->endpoint resolution the reference
gets from its JobManager (VERDICT r3 missing #1), grown into the HA
plane's liveness store.

The reference's clients never name a server port: ``QueryClientHelper``
connects to the JobManager (``--jobManagerHost``/``--jobManagerPort``) and
resolves *any* running job's queryable state by ``--jobId``
(``QueryClientHelper.java:82-92,121`` — ``client.getKvState(jobId, ...)``).
Here the control plane is a registry DIRECTORY: every ``ServingJob``
registers ``<jobId>.json`` (host, port, state, pid) on start and removes it
on stop, and clients resolve ``--jobId`` through it when no explicit
``--jobManagerPort`` is given.  Multiple serving jobs on one machine (or a
shared filesystem) are therefore addressable by jobId alone, like the
reference — no operator port wiring.

Liveness (the HA subsystem, serve/ha.py): an entry may carry a heartbeat
contract — ``ttl_s`` promises the writer refreshes ``heartbeat`` at least
that often (``ServingJob`` re-registers on the ``TPUMS_HEARTBEAT_S``
cadence).  Readers treat an entry whose heartbeat is past its promised TTL
as dead, exactly like a locally-recorded pid that no longer exists; dead
entries are garbage-collected on the next ``resolve()`` / ``list_jobs()``
pass instead of lingering forever.  Entries WITHOUT ``ttl_s`` (manual
registrations, older writers) are never TTL-checked — liveness there
remains pid-based only, the pre-HA behavior.

Replica sets: a replicated shard worker registers with ``replica_of`` (the
logical shard group id, e.g. ``"mysvc/shard-0"``), ``replica`` (its index
in the set) and ``ready`` (False while it is still replaying the journal —
the readiness gate clients honor during failover).  ``resolve_replicas``
returns the live members of a group.

Topology records (the elastic plane, serve/elastic.py): a job GROUP's
active shape lives in one ``kind="topology"`` record — ``(gen, shards,
replicas)`` plus a bounded history of superseded generations.  Publishes
are atomic (tmp + rename under a short-lived lock file) and CAS-guarded:
a publisher naming ``expect_gen`` that no longer matches loses with
``TopologyConflict`` instead of silently rolling the fleet back.  Unlike
endpoint registration, topology publish is NOT best-effort — a controller
that cannot record a cutover must know.  ``gc_generation_entries`` reaps
DEAD worker entries of superseded generations immediately (the TTL would
get them eventually; a cutover shouldn't leave corpses for readers to
re-judge until then).  A controller LEASE (``acquire_controller_lease``)
makes rescaling single-writer per group: the second controller refuses —
or defers, its choice — unless the holder's pid/heartbeat shows it dead,
in which case the lease is stolen with the same TOCTOU guard as entry
reaping.

Location: ``TPUMS_REGISTRY_DIR`` (deployment/shared-FS override), else
``<tmpdir>/flink_ms_tpu_registry`` — the same host-local convention as the
journal's default bus directory.  Registration is best-effort: registry
I/O failures never take down a serving job (a client then needs the
explicit port, which is exactly today's behavior).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from ..core.params import Params


def registry_dir() -> str:
    return os.environ.get("TPUMS_REGISTRY_DIR") or os.path.join(
        tempfile.gettempdir(), "flink_ms_tpu_registry"
    )


def heartbeat_interval_s() -> float:
    """Registry heartbeat cadence (``TPUMS_HEARTBEAT_S``, default 1 s)."""
    try:
        return max(float(os.environ.get("TPUMS_HEARTBEAT_S", 1.0)), 0.05)
    except ValueError:
        return 1.0


def replica_ttl_s() -> float:
    """Staleness TTL for heartbeat-bearing entries (``TPUMS_REPLICA_TTL_S``,
    default 5x the heartbeat interval).  The TTL must comfortably exceed
    the heartbeat cadence or a GC'd entry flaps on every scheduler hiccup."""
    try:
        v = os.environ.get("TPUMS_REPLICA_TTL_S")
        if v is not None:
            return max(float(v), 0.1)
    except ValueError:
        pass
    return 5.0 * heartbeat_interval_s()


def _load_json_retry(path: str, strict: bool = False):
    """Shared torn-read guard for every registry file read.

    Writers are atomic (tmp + rename/link), but a reader can still open a
    file mid-replacement on filesystems whose rename visibility is not a
    single point (NFS attribute caching, overlayfs copy-up), or catch a
    non-registry writer mid-write.  A JSON decode failure is therefore
    ambiguous: torn-mid-write or an actual corpse.  ONE short re-read
    disambiguates — a concurrent writer's rename lands within the backoff,
    so a live record is never judged dead off a single torn read.  A
    missing file stays an immediate None (no entry is not a torn entry).

    ``strict=True`` (the elastic client's topology refresh) re-raises the
    final failure instead of returning None, so callers can tell "no
    record" from "the registry is unreadable right now" and keep serving
    their last known state rather than silently treating an I/O blip as a
    dropped topology."""
    last_err: Optional[Exception] = None
    for attempt in (0, 1):
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            last_err = e
            if attempt == 0:
                time.sleep(0.002)
    if strict and last_err is not None:
        raise last_err
    return None


def _entry_path(job_id: str) -> str:
    # jobIds are caller-chosen strings: sanitize for the filesystem, and
    # append a short digest of the RAW id so distinct ids can never map to
    # one file (sanitizing alone would let "als/prod" overwrite or delete
    # "als_prod"'s live registration)
    import hashlib

    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in job_id)
    digest = hashlib.sha1(job_id.encode("utf-8")).hexdigest()[:8]
    return os.path.join(registry_dir(), f"{safe[:80]}-{digest}.json")


def register(
    job_id: str,
    host: str,
    port: int,
    state_name: str,
    *,
    replica_of: Optional[str] = None,
    replica: Optional[int] = None,
    ready: Optional[bool] = None,
    ttl_s: Optional[float] = None,
) -> None:
    """Record a serving job's endpoint (atomic write; best-effort).

    Re-registering IS the heartbeat: a writer that passed ``ttl_s`` calls
    this again on its heartbeat cadence (full-entry atomic rewrite — no
    read-modify-write race with a concurrent reaper)."""
    try:
        os.makedirs(registry_dir(), exist_ok=True)
        path = _entry_path(job_id)
        tmp = f"{path}.{os.getpid()}.tmp"
        import socket

        entry = {
            "job_id": job_id, "host": host, "port": int(port),
            "state": state_name, "pid": os.getpid(),
            # pid_host scopes the pid-liveness check: on a shared-FS
            # registry a pid is only meaningful on the machine that
            # recorded it (a wildcard bind says nothing about where)
            "pid_host": socket.gethostname(),
        }
        if replica_of is not None:
            entry["replica_of"] = replica_of
        if replica is not None:
            entry["replica"] = int(replica)
        if ready is not None:
            entry["ready"] = bool(ready)
        if ttl_s is not None:
            entry["ttl_s"] = float(ttl_s)
            entry["heartbeat"] = time.time()
        with open(tmp, "w") as f:
            json.dump(entry, f)
        os.replace(tmp, path)
    except OSError:
        pass


def unregister(job_id: str) -> None:
    try:
        os.unlink(_entry_path(job_id))
    except OSError:
        pass


def entry_is_dead(entry: dict, now: Optional[float] = None) -> bool:
    """True when this entry's job is provably gone: a locally-recorded pid
    that no longer exists, or a heartbeat contract (``ttl_s``) the writer
    has broken.  Entries without either signal are presumed alive."""
    pid = entry.get("pid")
    if isinstance(pid, int) and _pid_is_ours_and_dead(entry):
        return True
    ttl = entry.get("ttl_s")
    hb = entry.get("heartbeat")
    if isinstance(ttl, (int, float)) and isinstance(hb, (int, float)):
        if (time.time() if now is None else now) - hb > ttl:
            return True
    return False


def _reap_if_unchanged(path: str, entry: dict) -> Optional[dict]:
    """GC a dead entry, guarding the reap TOCTOU: a supervisor may have
    re-registered the job at this path since our read — only unlink if the
    file still carries the same (pid, heartbeat) we judged dead.  Returns
    the FRESH entry when one replaced the dead one, else None."""
    current = _load_json_retry(path)
    if current is None:
        return None
    if (
        isinstance(current, dict)
        and current.get("pid") == entry.get("pid")
        and current.get("heartbeat") == entry.get("heartbeat")
    ):
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    if isinstance(current, dict) and "port" in current \
            and not entry_is_dead(current):
        return current
    return None


def resolve(job_id: str) -> Optional[dict]:
    """-> the registered entry for job_id, or None.

    A SIGKILL'd ServingJob never runs its unregister cleanup, so an entry
    recorded by THIS machine (pid_host matches) whose pid is dead — or any
    entry whose heartbeat contract has lapsed — is treated as no-entry
    (and reaped): clients then fall back to the explicit-port defaults
    instead of getting connection-refused on a stale endpoint.  Entries
    recorded elsewhere (shared-FS registry) are never pid-checked: the pid
    is meaningless across machines; their TTL still applies."""
    path = _entry_path(job_id)
    entry = _load_json_retry(path)
    if not isinstance(entry, dict) or "port" not in entry:
        return None
    if entry_is_dead(entry):
        return _reap_if_unchanged(path, entry)
    return entry


def list_jobs(gc: bool = True) -> List[dict]:
    """Every live entry in the registry (GC'ing dead ones on the way,
    unless ``gc=False``).  The ops/discovery surface: replica resolution,
    supervisors, and the chaos harness all build on this scan."""
    out: List[dict] = []
    try:
        names = os.listdir(registry_dir())
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(registry_dir(), name)
        entry = _load_json_retry(path)
        if not isinstance(entry, dict) or "port" not in entry:
            continue
        if entry_is_dead(entry):
            if gc:
                fresh = _reap_if_unchanged(path, entry)
                if fresh is not None:
                    out.append(fresh)
            continue
        out.append(entry)
    return out


def resolve_replicas(replica_of: str) -> List[dict]:
    """Live members of a replica group, sorted by replica index.  Entries
    whose ``ready`` flag is False are included (callers that must not send
    traffic to a replaying replica filter on ``ready`` themselves — a
    supervisor, by contrast, needs to see them to NOT respawn them)."""
    members = [
        e for e in list_jobs() if e.get("replica_of") == replica_of
    ]
    members.sort(key=lambda e: (e.get("replica", 0), e.get("job_id", "")))
    return members


# ---------------------------------------------------------------------------
# tenant namespaces (the multi-tenant fleet, serve/rollout.py + admission)
# ---------------------------------------------------------------------------

# A tenant is a NAME PREFIX on group/job identifiers: ``acme::als`` is
# tenant "acme"'s serving group "als".  Everything derived from the group
# string — worker job ids, replica groups, generation groups, topology
# records, controller leases, snapshot scopes — inherits the prefix, so
# two tenants' fleets coexist in one registry directory with zero shared
# records and per-tenant GC that provably cannot touch a neighbor.

TENANT_SEP = "::"


def default_tenant() -> Optional[str]:
    """The ambient tenant (``TPUMS_TENANT``), or None for the shared
    (un-prefixed) namespace — the single-tenant deployments' default."""
    t = os.environ.get("TPUMS_TENANT", "").strip()
    return t or None


def qualify_group(group: str, tenant: Optional[str] = None) -> str:
    """Tenant-scope a group name -> ``<tenant>::<group>``.

    ``tenant=None`` uses the ambient ``TPUMS_TENANT``; an explicit empty
    string pins the shared namespace regardless of environment.  Already
    qualified names pass through unchanged (idempotent, so controllers
    and clients can both call it on the same name)."""
    if TENANT_SEP in group:
        return group
    t = default_tenant() if tenant is None else (tenant.strip() or None)
    if not t:
        return group
    if TENANT_SEP in t or "/" in t or "\t" in t or "\n" in t:
        raise ValueError(f"bad tenant name: {t!r}")
    return f"{t}{TENANT_SEP}{group}"


def split_tenant(name: str) -> Tuple[Optional[str], str]:
    """``"acme::als@g3/shard-0"`` -> ("acme", "als@g3/shard-0");
    un-prefixed names -> (None, name)."""
    if TENANT_SEP in name:
        t, _, base = name.partition(TENANT_SEP)
        return (t or None), base
    return None, name


def tenant_of(name: str) -> Optional[str]:
    return split_tenant(name)[0]


def _entry_tenant(entry: dict) -> Optional[str]:
    return tenant_of(entry.get("replica_of") or entry.get("job_id") or "")


def list_tenants() -> List[str]:
    """Tenants with any registry presence (live worker entries or
    topology records), sorted.  The shared namespace is not a tenant and
    is never listed."""
    seen = set()
    for e in list_jobs(gc=False):
        t = _entry_tenant(e)
        if t:
            seen.add(t)
    try:
        names = os.listdir(registry_dir())
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".topo.json"):
            continue
        rec = _read_record(os.path.join(registry_dir(), name), "topology")
        if rec:
            t = tenant_of(rec.get("group") or "")
            if t:
                seen.add(t)
    return sorted(seen)


def list_tenant_jobs(tenant: Optional[str], gc: bool = True) -> List[dict]:
    """Live entries belonging to one tenant's namespace (``tenant=None``
    selects the shared namespace)."""
    return [e for e in list_jobs(gc=gc) if _entry_tenant(e) == tenant]


def gc_tenant_entries(tenant: str) -> int:
    """Reap DEAD worker entries of ONE tenant -> count reaped.

    The isolation guarantee of the namespace scheme, stated as an
    operation: this can only ever unlink entries whose identifiers carry
    ``<tenant>::`` — other tenants and the shared namespace are
    structurally out of reach.  Raw dir scan for the same reason as
    ``gc_generation_entries``."""
    if not tenant:
        raise ValueError("gc_tenant_entries needs a tenant name")
    reaped = 0
    try:
        names = os.listdir(registry_dir())
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(registry_dir(), name)
        entry = _load_json_retry(path)
        if not isinstance(entry, dict) or "port" not in entry:
            continue
        if _entry_tenant(entry) != tenant:
            continue
        if entry_is_dead(entry) and _reap_if_unchanged(path, entry) is None:
            reaped += 1
    return reaped


# ---------------------------------------------------------------------------
# region namespaces (the geo-distributed plane, serve/georepl.py)
# ---------------------------------------------------------------------------

# A region is the OUTERMOST name prefix on group/job identifiers:
# ``eu@@acme::als`` is region "eu"'s view of tenant "acme"'s serving group
# "als".  Same discipline as tenant namespaces, one level further out:
# every id derived from a region-qualified group — worker job ids, replica
# groups, generation groups, topology records, controller leases, snapshot
# scopes, alert scopes — inherits the prefix, so a follower fleet in one
# region shares zero registry records with the home fleet, and region GC
# structurally cannot touch another region's entries.

REGION_SEP = "@@"


def default_region() -> Optional[str]:
    """The ambient region (``TPUMS_GEO_REGION``), or None for the
    unscoped namespace — single-region deployments' default."""
    r = os.environ.get("TPUMS_GEO_REGION", "").strip()
    return r or None


def qualify_region(name: str, region: Optional[str] = None) -> str:
    """Region-scope a group/job name -> ``<region>@@<name>``.

    ``region=None`` uses the ambient ``TPUMS_GEO_REGION``; an explicit
    empty string pins the unscoped namespace regardless of environment.
    Already region-qualified names pass through unchanged (idempotent).
    Applied OUTSIDE tenant qualification: ``eu@@acme::als``."""
    if REGION_SEP in name:
        return name
    r = default_region() if region is None else (region.strip() or None)
    if not r:
        return name
    if (REGION_SEP in r or TENANT_SEP in r or "/" in r
            or "\t" in r or "\n" in r):
        raise ValueError(f"bad region name: {r!r}")
    return f"{r}{REGION_SEP}{name}"


def split_region(name: str) -> Tuple[Optional[str], str]:
    """``"eu@@acme::als@g3/shard-0"`` -> ("eu", "acme::als@g3/shard-0");
    unscoped names -> (None, name)."""
    if REGION_SEP in name:
        r, _, base = name.partition(REGION_SEP)
        return (r or None), base
    return None, name


def region_of(name: str) -> Optional[str]:
    return split_region(name)[0]


def _entry_region(entry: dict) -> Optional[str]:
    return region_of(entry.get("replica_of") or entry.get("job_id") or "")


def list_regions() -> List[str]:
    """Regions with any registry presence (live worker entries or topology
    records), sorted.  The unscoped namespace is not a region."""
    seen = set()
    for e in list_jobs(gc=False):
        r = _entry_region(e)
        if r:
            seen.add(r)
    try:
        names = os.listdir(registry_dir())
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".topo.json"):
            continue
        rec = _read_record(os.path.join(registry_dir(), name), "topology")
        if rec:
            r = region_of(rec.get("group") or "")
            if r:
                seen.add(r)
    return sorted(seen)


def list_region_jobs(region: Optional[str], gc: bool = True) -> List[dict]:
    """Live entries belonging to one region's namespace (``region=None``
    selects the unscoped namespace)."""
    return [e for e in list_jobs(gc=gc) if _entry_region(e) == region]


# ---------------------------------------------------------------------------
# edge-proxy namespace (serve/edge.py)
# ---------------------------------------------------------------------------

EDGE_PREFIX = "edge/"


def edge_group(group: str, region: Optional[str] = None) -> str:
    """The registry replica-group carrying a serving group's EDGE PROXY
    endpoints — ``edge/<region>@@<tenant>::<group>``.

    Proxies register under it with ``replica_of=edge_group(g)`` (one
    entry per proxy, ``replica=<index>``) and re-register on the
    heartbeat cadence like any worker, so ``resolve_replicas`` is the
    one discovery path clients, smokes and the scraper all share.
    Distinct from the group's shard topology record: the edge tier is
    stateless and has no generations — proxies follow the data plane's
    topology record, they never appear in it."""
    return f"{EDGE_PREFIX}{qualify_region(qualify_group(group), region)}"


def gc_region_entries(region: str) -> int:
    """Reap DEAD worker entries of ONE region -> count reaped.  Same
    structural-isolation statement as ``gc_tenant_entries``: only entries
    whose identifiers carry ``<region>@@`` are reachable."""
    if not region:
        raise ValueError("gc_region_entries needs a region name")
    reaped = 0
    try:
        names = os.listdir(registry_dir())
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(registry_dir(), name)
        entry = _load_json_retry(path)
        if not isinstance(entry, dict) or "port" not in entry:
            continue
        if _entry_region(entry) != region:
            continue
        if entry_is_dead(entry) and _reap_if_unchanged(path, entry) is None:
            reaped += 1
    return reaped


def _pid_is_ours_and_dead(entry: dict) -> bool:
    import socket

    if entry.get("pid_host") != socket.gethostname():
        return False  # recorded by another machine (or a pre-pid_host
        # entry): liveness is unknowable here, keep the entry
    try:
        os.kill(entry["pid"], 0)
    except ProcessLookupError:
        return True
    except OSError:
        pass  # EPERM etc.: the process exists, just not ours
    return False


# ---------------------------------------------------------------------------
# topology records + controller lease (the elastic plane, serve/elastic.py)
# ---------------------------------------------------------------------------

TOPOLOGY_HISTORY = 8  # superseded generations kept in the record


class TopologyConflict(RuntimeError):
    """A CAS publish lost: the group's generation moved under the caller."""


def _group_path(group: str, suffix: str) -> str:
    import hashlib

    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in group)
    digest = hashlib.sha1(group.encode("utf-8")).hexdigest()[:8]
    return os.path.join(registry_dir(), f"{safe[:80]}-{digest}.{suffix}")


def _topology_path(group: str) -> str:
    # distinct suffix so a JOB registered under the group's name can never
    # collide with the group's topology record (both end in .json; readers
    # of either kind validate the payload, not the filename)
    return _group_path(group, "topo.json")


def _read_record(path: str, kind: str, strict: bool = False
                 ) -> Optional[dict]:
    record = _load_json_retry(path, strict=strict)
    if not isinstance(record, dict) or record.get("kind") != kind:
        return None
    return record


def resolve_topology(group: str, strict: bool = False) -> Optional[dict]:
    """The group's active topology record ``{gen, shards, replicas, ...}``,
    or None when no generation was ever published.  ``strict=True`` raises
    the underlying ``OSError``/``ValueError`` when the record exists but
    cannot be read — clients refreshing a topology must distinguish "gone"
    (rebuild against defaults) from "unreadable" (keep the generation they
    have)."""
    return _read_record(_topology_path(group), "topology", strict=strict)


class _GroupLock:
    """Short-lived O_EXCL lock file serializing read-modify-write of one
    group's records.  A lock older than ``stale_s`` is presumed abandoned
    (its holder crashed between create and unlink) and broken."""

    def __init__(self, path: str, timeout_s: float = 2.0,
                 stale_s: float = 5.0):
        self.path = path + ".lock"
        self.timeout_s = timeout_s
        self.stale_s = stale_s

    def __enter__(self):
        deadline = time.time() + self.timeout_s
        while True:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                return self
            except FileExistsError:
                try:
                    if time.time() - os.path.getmtime(self.path) > self.stale_s:
                        os.unlink(self.path)
                        continue
                except OSError:
                    continue  # holder released between stat and unlink
                if time.time() > deadline:
                    raise TimeoutError(
                        f"group lock busy: {self.path}") from None
                time.sleep(0.01)

    def __exit__(self, *exc):
        try:
            os.unlink(self.path)
        except OSError:
            pass


def publish_topology(
    group: str,
    shards: int,
    replicas: int = 1,
    *,
    expect_gen: Optional[int] = None,
    controller: Optional[str] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Atomically publish the group's next topology generation -> record.

    The new generation is always ``current + 1`` (1 for a fresh group).
    ``expect_gen`` is the CAS guard: a controller that planned the cutover
    against generation G passes ``expect_gen=G``, and if some other writer
    advanced the record meanwhile this raises ``TopologyConflict`` instead
    of overwriting the newer topology.  The superseded generation joins a
    bounded ``history`` (stale-generation GC: the record never grows past
    ``TOPOLOGY_HISTORY`` entries).  NOT best-effort: I/O failures raise.

    ``extra``: additional record fields (cannot shadow the protocol
    fields).  The rollout controller binds the generation's MODEL here
    (``{"model": {journal_dir, topic, model_id, ...}}``); a generation's
    model binding follows it into ``history``, which is what makes
    one-command rollback possible (serve/rollout.py)."""
    if shards < 1 or replicas < 1:
        raise ValueError("need shards >= 1 and replicas >= 1")
    os.makedirs(registry_dir(), exist_ok=True)
    path = _topology_path(group)
    import socket

    with _GroupLock(path):
        current = _read_record(path, "topology")
        cur_gen = int(current["gen"]) if current else 0
        if expect_gen is not None and cur_gen != int(expect_gen):
            raise TopologyConflict(
                f"group {group!r} is at generation {cur_gen}, "
                f"publisher expected {expect_gen}"
            )
        history = list(current.get("history", ())) if current else []
        if current:
            superseded = {
                "gen": current["gen"], "shards": current["shards"],
                "replicas": current["replicas"],
                "published_at": current.get("published_at"),
            }
            if "model" in current:
                superseded["model"] = current["model"]
            history.append(superseded)
            history = history[-TOPOLOGY_HISTORY:]
        record = {
            "kind": "topology", "group": group, "gen": cur_gen + 1,
            "shards": int(shards), "replicas": int(replicas),
            "published_at": time.time(),
            "controller": controller
            or f"{socket.gethostname()}:{os.getpid()}",
            "history": history,
        }
        if extra:
            for k, v in extra.items():
                record.setdefault(k, v)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
    return record


def drop_topology(group: str) -> None:
    """Remove the group's topology record (teardown; best-effort)."""
    try:
        os.unlink(_topology_path(group))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# push-plane subscription epochs (serve/push.py)
# ---------------------------------------------------------------------------

def _push_epoch_path(scope: str) -> str:
    return _group_path(f"pushes/{scope}", "push.json")


def next_push_epoch(scope: str) -> int:
    """Atomically claim the scope's next subscription epoch -> int >= 1.

    Every push engine (one per serving process that ever accepts a
    SUBSCRIBE) claims one epoch at startup and mints subscription ids as
    ``<epoch>-<n>``, so ids stay globally unique across replica restarts,
    reshards and failovers — the property the zero-miss/zero-dup sequence
    audit leans on: a RESUME that lands on a replica which never saw the
    subscription can only answer with a FRESH id + snapshot, never reuse
    the old id with a colliding sequence space.  Same read-modify-write
    discipline as ``publish_topology`` (group lock + tmp + rename)."""
    os.makedirs(os.path.dirname(_push_epoch_path(scope)) or ".",
                exist_ok=True)
    path = _push_epoch_path(scope)
    with _GroupLock(path):
        current = _read_record(path, "push_epoch")
        epoch = (int(current["epoch"]) if current else 0) + 1
        record = {"kind": "push_epoch", "scope": scope, "epoch": epoch,
                  "claimed_at": time.time(), "pid": os.getpid()}
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
    return epoch


# ---------------------------------------------------------------------------
# snapshot manifests (serve/snapshot.py publishes, fleet scrape reads)
# ---------------------------------------------------------------------------

def snapshot_scope(
    group: Optional[str], topic: Optional[str], num_shards: int, shard: int
) -> str:
    """One registry record per (group-or-topic, sharding, shard): the
    LATEST published snapshot for that slice."""
    return f"snap/{group or topic or 'default'}/{num_shards}/{shard}"


def _snapshot_path(scope: str) -> str:
    return _group_path(scope, "snap.json")


def publish_snapshot(scope: str, manifest: dict) -> None:
    """Register the slice's latest snapshot manifest.  Best-effort by
    design: bootstrap resolves snapshots from the data dirs (which survive
    a wiped registry); this record only feeds fleet observability."""
    os.makedirs(registry_dir(), exist_ok=True)
    path = _snapshot_path(scope)
    record = {"kind": "snapshot", "scope": scope,
              "published_at": time.time(), "manifest": dict(manifest)}
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def resolve_snapshot(scope: str) -> Optional[dict]:
    """The slice's latest registered snapshot manifest, or None."""
    record = _read_record(_snapshot_path(scope), "snapshot")
    return record.get("manifest") if record else None


# ---------------------------------------------------------------------------
# alert records (obs/watch.py publishes, HEALTH hints and fleet_signals
# read) — same best-effort file-per-record shape as snapshot manifests.
# Records carry their own TTL so a dead watcher's last word expires
# instead of pinning stale alerts onto every HEALTH reply forever.
# ---------------------------------------------------------------------------

def _alerts_path(scope: str) -> str:
    return _group_path(f"alerts/{scope}", "alerts.json")


def publish_alerts(scope: str, summary: dict, ttl_s: float = 15.0) -> None:
    """Publish a watcher's alert summary (``RulesEngine.summary()`` shape:
    ``{"firing", "max_severity", "max_severity_level", "alerts"}``) under
    ``scope`` (a group name, or ``"fleet"`` for a whole-fleet watcher)."""
    os.makedirs(registry_dir(), exist_ok=True)
    path = _alerts_path(scope)
    record = {"kind": "alerts", "scope": scope,
              "published_at": time.time(), "ttl_s": float(ttl_s),
              "summary": dict(summary)}
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def resolve_alerts(scope: Optional[str] = None) -> Optional[dict]:
    """The current alert summary: one scope's fresh record, or — with no
    scope — every fresh record merged (firing counts sum, severities take
    the max).  Expired records are GC'd on the way past.  None when no
    watcher has published anything fresh."""
    if scope is not None:
        record = _read_record(_alerts_path(scope), "alerts")
        if record is None:
            return None
        if time.time() - record.get("published_at", 0) > \
                record.get("ttl_s", 15.0):
            drop_alerts(scope)
            return None
        return record.get("summary")
    merged: Optional[dict] = None
    try:
        names = os.listdir(registry_dir())
    except OSError:
        return None
    now = time.time()
    for fname in names:
        if not fname.startswith("alerts_") or \
                not fname.endswith(".alerts.json"):
            continue
        path = os.path.join(registry_dir(), fname)
        record = _read_record(path, "alerts")
        if record is None:
            continue
        if now - record.get("published_at", 0) > record.get("ttl_s", 15.0):
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        s = record.get("summary", {})
        if merged is None:
            merged = {"firing": 0, "max_severity": None,
                      "max_severity_level": 0, "alerts": []}
        merged["firing"] += int(s.get("firing", 0))
        merged["alerts"].extend(s.get("alerts", []))
        if s.get("max_severity_level", 0) > merged["max_severity_level"]:
            merged["max_severity_level"] = s["max_severity_level"]
            merged["max_severity"] = s.get("max_severity")
    return merged


def drop_alerts(scope: str) -> None:
    """Remove a scope's alert record (watcher teardown; best-effort)."""
    try:
        os.unlink(_alerts_path(scope))
    except OSError:
        pass


def generation_of(entry: dict, group: str, gen_sep: str = "@g"
                  ) -> Optional[int]:
    """Parse the topology generation out of a worker entry's shard-group id
    (``<group>@g<gen>/shard-<i>``); None for entries outside ``group``."""
    replica_of = entry.get("replica_of") or ""
    prefix = f"{group}{gen_sep}"
    if not replica_of.startswith(prefix):
        return None
    gen_s = replica_of[len(prefix):].split("/", 1)[0]
    try:
        return int(gen_s)
    except ValueError:
        return None


def gc_generation_entries(group: str, active_gen: int) -> int:
    """Reap DEAD worker entries of generations < ``active_gen`` -> count.

    Live old-generation workers are left alone — a cutover drains them
    deliberately (serve/elastic.py), and a worker that outlives its drain
    window still answers in-flight clients.  Dead ones would be TTL-GC'd
    eventually; after a cutover they are provably garbage NOW.

    Scans the raw registry dir (NOT ``list_jobs``, which filters dead
    entries out of its result whether or not it GCs them)."""
    reaped = 0
    try:
        names = os.listdir(registry_dir())
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(registry_dir(), name)
        entry = _load_json_retry(path)
        if not isinstance(entry, dict) or "port" not in entry:
            continue
        gen = generation_of(entry, group)
        if gen is None or gen >= active_gen:
            continue
        if entry_is_dead(entry) and _reap_if_unchanged(path, entry) is None:
            reaped += 1
    return reaped


def _controller_path(group: str) -> str:
    return _group_path(group, "ctl.json")


def acquire_controller_lease(group: str, ttl_s: Optional[float] = None
                             ) -> Optional[str]:
    """Try to become the group's single scaling controller -> lease token,
    or None while another live controller holds the lease.

    The lease is a registry-style heartbeat contract: the holder refreshes
    within ``ttl_s`` (default: the replica TTL) or is presumed dead, and a
    dead holder's lease (pid gone, or heartbeat lapsed) is STOLEN —
    serialized through a link-based steal lock so two stealers cannot
    both win one corpse.

    Acquisition is link-based so the lease file appears ATOMICALLY with
    its full contents: an O_EXCL create would expose an empty file for
    the duration of the winner's write, and a concurrent acquirer reading
    that window judged the record a torn-write corpse and claimed it too
    — two winners for one fresh lease."""
    import socket
    import uuid

    os.makedirs(registry_dir(), exist_ok=True)
    path = _controller_path(group)
    token = uuid.uuid4().hex
    entry = {
        "kind": "controller", "group": group, "token": token,
        "pid": os.getpid(), "pid_host": socket.gethostname(),
        "heartbeat": time.time(),
        "ttl_s": replica_ttl_s() if ttl_s is None else float(ttl_s),
    }
    data = json.dumps(entry)
    tmp = f"{path}.{os.getpid()}.{token[:8]}.tmp"
    try:
        with open(tmp, "w") as f:
            f.write(data)
        try:
            os.link(tmp, path)
            return token
        except FileExistsError:
            pass
        current = _read_record(path, "controller")
        if current is not None and not entry_is_dead(current):
            return None
        # unreadable/foreign record (atomic creation means the normal
        # path can no longer produce one) OR a dead holder's lease:
        # exactly ONE claimant recovers it.  Renaming ``path`` aside
        # cannot be the mutual exclusion — the first winner re-creates
        # ``path``, which a second stealer holding a stale read of the
        # corpse would then rename aside again.  Instead a link-based
        # steal LOCK serializes recovery: one claimant creates it,
        # re-judges the record under the lock, and replaces atomically.
        # A lock orphaned by a claimant dying mid-steal goes stale
        # after the lease TTL and is cleared for the next attempt.
        lock = f"{path}.steal"
        try:
            os.link(tmp, lock)
        except FileExistsError:
            try:
                if time.time() - os.stat(lock).st_mtime > entry["ttl_s"]:
                    os.unlink(lock)
            except OSError:
                pass
            return None
        try:
            check = _read_record(path, "controller")
            if check is not None and not entry_is_dead(check):
                return None
            os.replace(tmp, path)
            return token
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass
    except OSError:
        return None
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def refresh_controller_lease(group: str, token: str) -> bool:
    """Heartbeat the lease -> True while this token still holds it."""
    path = _controller_path(group)
    current = _read_record(path, "controller")
    if current is None or current.get("token") != token:
        return False
    current["heartbeat"] = time.time()
    tmp = f"{path}.{os.getpid()}.hb.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(current, f)
        os.replace(tmp, path)
    except OSError:
        return False
    return True


def release_controller_lease(group: str, token: str) -> None:
    """Drop the lease iff this token still holds it (best-effort)."""
    path = _controller_path(group)
    current = _read_record(path, "controller")
    if current is not None and current.get("token") == token:
        try:
            os.unlink(path)
        except OSError:
            pass


def merge_endpoint(entry: Optional[dict], explicit_host: Optional[str],
                   default_host: str = "localhost",
                   default_port: int = 6123) -> Tuple[str, int]:
    """Merge a registry entry with a caller-supplied host into (host, port).

    The single place that encodes the precedence both client surfaces
    (flag-based CLIs and positional REPLs) share: an explicit host always
    wins; a registered wildcard bind (0.0.0.0) is reached via the explicit
    host or loopback default; no entry means the reference defaults."""
    host = explicit_host or default_host
    if entry is None:
        return host, default_port
    reg_host = entry.get("host") or ""
    if explicit_host is None and reg_host and reg_host != "0.0.0.0":
        host = reg_host
    return host, int(entry["port"])


def resolve_endpoint(params: Params, default_port: int = 6123
                     ) -> Tuple[str, int]:
    """(host, port) for a client CLI, with JobManager-style jobId routing.

    Precedence mirrors the reference's surface: an EXPLICIT
    ``--jobManagerPort`` wins (direct wiring always works); otherwise
    ``--jobId`` resolves through the registry like ``getKvState(jobId,...)``
    through the JobManager; otherwise the reference's defaults
    (localhost:6123)."""
    explicit_host = (
        params.get("jobManagerHost") if params.has("jobManagerHost") else None
    )
    if params.has("jobManagerPort"):
        return (explicit_host or "localhost",
                params.get_int("jobManagerPort", default_port))
    job_id = params.get("jobId")
    entry = resolve(job_id) if job_id else None
    return merge_endpoint(entry, explicit_host, default_port=default_port)
