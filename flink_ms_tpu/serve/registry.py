"""Job location registry — the jobId->endpoint resolution the reference
gets from its JobManager (VERDICT r3 missing #1).

The reference's clients never name a server port: ``QueryClientHelper``
connects to the JobManager (``--jobManagerHost``/``--jobManagerPort``) and
resolves *any* running job's queryable state by ``--jobId``
(``QueryClientHelper.java:82-92,121`` — ``client.getKvState(jobId, ...)``).
Here the control plane is a registry DIRECTORY: every ``ServingJob``
registers ``<jobId>.json`` (host, port, state, pid) on start and removes it
on stop, and clients resolve ``--jobId`` through it when no explicit
``--jobManagerPort`` is given.  Multiple serving jobs on one machine (or a
shared filesystem) are therefore addressable by jobId alone, like the
reference — no operator port wiring.

Location: ``TPUMS_REGISTRY_DIR`` (deployment/shared-FS override), else
``<tmpdir>/flink_ms_tpu_registry`` — the same host-local convention as the
journal's default bus directory.  Registration is best-effort: registry
I/O failures never take down a serving job (a client then needs the
explicit port, which is exactly today's behavior).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional, Tuple

from ..core.params import Params


def registry_dir() -> str:
    return os.environ.get("TPUMS_REGISTRY_DIR") or os.path.join(
        tempfile.gettempdir(), "flink_ms_tpu_registry"
    )


def _entry_path(job_id: str) -> str:
    # jobIds are caller-chosen strings: sanitize for the filesystem, and
    # append a short digest of the RAW id so distinct ids can never map to
    # one file (sanitizing alone would let "als/prod" overwrite or delete
    # "als_prod"'s live registration)
    import hashlib

    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in job_id)
    digest = hashlib.sha1(job_id.encode("utf-8")).hexdigest()[:8]
    return os.path.join(registry_dir(), f"{safe[:80]}-{digest}.json")


def register(job_id: str, host: str, port: int, state_name: str) -> None:
    """Record a serving job's endpoint (atomic write; best-effort)."""
    try:
        os.makedirs(registry_dir(), exist_ok=True)
        path = _entry_path(job_id)
        tmp = f"{path}.{os.getpid()}.tmp"
        import socket

        with open(tmp, "w") as f:
            json.dump({
                "job_id": job_id, "host": host, "port": int(port),
                "state": state_name, "pid": os.getpid(),
                # pid_host scopes the pid-liveness check: on a shared-FS
                # registry a pid is only meaningful on the machine that
                # recorded it (a wildcard bind says nothing about where)
                "pid_host": socket.gethostname(),
            }, f)
        os.replace(tmp, path)
    except OSError:
        pass


def unregister(job_id: str) -> None:
    try:
        os.unlink(_entry_path(job_id))
    except OSError:
        pass


def resolve(job_id: str) -> Optional[dict]:
    """-> the registered entry for job_id, or None.

    A SIGKILL'd ServingJob never runs its unregister cleanup, so an entry
    recorded by THIS machine (pid_host matches) whose pid is dead is
    treated as no-entry (and reaped) — clients then fall back to the
    explicit-port defaults instead of getting connection-refused on a
    stale endpoint.  Entries recorded elsewhere (shared-FS registry) are
    never pid-checked: the pid is meaningless across machines."""
    path = _entry_path(job_id)
    try:
        with open(path) as f:
            entry = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(entry, dict) or "port" not in entry:
        return None
    pid = entry.get("pid")
    if isinstance(pid, int) and _pid_is_ours_and_dead(entry):
        # narrow the reap TOCTOU: a supervisor may have re-registered the
        # job at this path since our read — only unlink if the file still
        # carries the dead pid we just checked
        try:
            with open(path) as f:
                current = json.load(f)
        except (OSError, ValueError):
            return None
        if current.get("pid") == pid:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        return current if isinstance(current, dict) and "port" in current \
            else None
    return entry


def _pid_is_ours_and_dead(entry: dict) -> bool:
    import socket

    if entry.get("pid_host") != socket.gethostname():
        return False  # recorded by another machine (or a pre-pid_host
        # entry): liveness is unknowable here, keep the entry
    try:
        os.kill(entry["pid"], 0)
    except ProcessLookupError:
        return True
    except OSError:
        pass  # EPERM etc.: the process exists, just not ours
    return False


def merge_endpoint(entry: Optional[dict], explicit_host: Optional[str],
                   default_host: str = "localhost",
                   default_port: int = 6123) -> Tuple[str, int]:
    """Merge a registry entry with a caller-supplied host into (host, port).

    The single place that encodes the precedence both client surfaces
    (flag-based CLIs and positional REPLs) share: an explicit host always
    wins; a registered wildcard bind (0.0.0.0) is reached via the explicit
    host or loopback default; no entry means the reference defaults."""
    host = explicit_host or default_host
    if entry is None:
        return host, default_port
    reg_host = entry.get("host") or ""
    if explicit_host is None and reg_host and reg_host != "0.0.0.0":
        host = reg_host
    return host, int(entry["port"])


def resolve_endpoint(params: Params, default_port: int = 6123
                     ) -> Tuple[str, int]:
    """(host, port) for a client CLI, with JobManager-style jobId routing.

    Precedence mirrors the reference's surface: an EXPLICIT
    ``--jobManagerPort`` wins (direct wiring always works); otherwise
    ``--jobId`` resolves through the registry like ``getKvState(jobId,...)``
    through the JobManager; otherwise the reference's defaults
    (localhost:6123)."""
    explicit_host = (
        params.get("jobManagerHost") if params.has("jobManagerHost") else None
    )
    if params.has("jobManagerPort"):
        return (explicit_host or "localhost",
                params.get_int("jobManagerPort", default_port))
    job_id = params.get("jobId")
    entry = resolve(job_id) if job_id else None
    return merge_endpoint(entry, explicit_host, default_port=default_port)
