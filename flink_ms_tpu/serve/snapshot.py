"""Durable, discoverable per-shard snapshot artifacts — the O(state)
bootstrap path that replaces O(history) journal replay for HA respawns and
elastic cutovers (serve/ha.py, serve/elastic.py).

A snapshot is a columnar dump of one worker's owned table slice at an
exact journal offset:

    <journal_dir>/<topic>.snapshots/
        snap-<num_shards>-<shard>-<offset>-<ns>/
            keys.txt        newline-delimited key column
            vals.txt        newline-delimited value column (line-aligned)
            MANIFEST.json   {format, topology_group, gen, shard,
                             num_shards, offset, rows, checksum, ts}

The two-file columnar layout exists so restore goes straight through
``ModelTable.put_many_columns`` (one C-level split per column, one
dict.update per table shard — the 791k rows/s ingest path) instead of
per-row puts.  ``checksum`` is a crc32 over both column files; restore
verifies it and a mismatch raises ``SnapshotCorruptError`` so the caller
falls down the chain: bad checksum -> older snapshot -> full journal
replay.  Publication is crash-safe: columns are written into a tmp dir,
fsynced, and renamed — a SIGKILL mid-write leaves only an invisible tmp
dir, never a half-snapshot under a valid name.

Resolution for a bootstrapping worker (owner = ``(shard, num_shards)``):

- fast path: the newest valid snapshot with EXACTLY the worker's
  ``(num_shards, shard)`` identity — its key slice is the worker's key
  slice, so one file bulk-loads the whole state and the tail replays from
  that snapshot's own offset.
- resharded path (elastic g+1 with a different worker count): the newest
  complete FAMILY — one snapshot per shard of some source ``num_shards``
  — bulk-loaded with a vectorized hash%N ownership filter per member;
  the tail replays from the family's MINIMUM member offset (last-writer-
  wins replay makes re-applied rows convergent, never regressive).

Manifests are additionally registered through ``serve/registry.py``
(best-effort, ``kind="snapshot"`` records) so the fleet scrape can see
each shard's latest published snapshot without touching the data dirs.
"""

from __future__ import annotations

import json
import os
import sys
import time
import zlib
from typing import Callable, List, Optional, Sequence, Tuple

SNAP_FORMAT = "tsv-columns/1"
# O(state) arena snapshots (serve/arena.py): one reflink/extent copy of the
# live mmap'd arena file instead of a serialize — no checksum pass (rows are
# seqlock-framed and self-describing; load verifies the row count), so
# publish cost is O(resident bytes moved), O(1) on reflink filesystems.
ARENA_FORMAT = "arena/1"
_MANIFEST = "MANIFEST.json"
_KEYS = "keys.txt"
_VALS = "vals.txt"
_ARENA = "arena.dat"


class SnapshotCorruptError(RuntimeError):
    """A snapshot member failed checksum/shape verification."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"snapshot {path} corrupt: {detail}")
        self.path = path
        self.detail = detail


def snapshot_root(journal_dir: str, topic: str) -> str:
    return os.path.join(journal_dir, f"{topic}.snapshots")


def snapshot_keep() -> int:
    try:
        return max(int(os.environ.get("TPUMS_SNAPSHOT_KEEP", 2)), 1)
    except ValueError:
        return 2


def _columns_checksum(keys_b: bytes, vals_b: bytes) -> int:
    return zlib.crc32(vals_b, zlib.crc32(keys_b))


# -- publication -------------------------------------------------------------

def publish(
    root: str,
    table,
    offset: int,
    *,
    shard: int = 0,
    num_shards: int = 1,
    group: Optional[str] = None,
    gen: Optional[int] = None,
    topic: Optional[str] = None,
    keep: Optional[int] = None,
) -> dict:
    """Write one snapshot artifact for (table, offset); returns the
    manifest (with its ``path``).  The caller guarantees the table is
    consistent with ``offset`` (the consume loop publishes between
    chunks, exactly like checkpoints).  An arena table (anything with
    ``quiesce_copy``) publishes the O(state) ``arena/1`` format; dict
    tables publish the portable columnar format."""
    if hasattr(table, "quiesce_copy"):
        return _publish_arena(
            root, table, offset, shard=shard, num_shards=num_shards,
            group=group, gen=gen, topic=topic, keep=keep)
    with table._lock:
        shards_copy = [dict(s) for s in table._shards]
    keys: List[str] = []
    vals: List[str] = []
    for s in shards_copy:
        keys.extend(s.keys())
        vals.extend(s.values())
    keys_b = ("\n".join(keys) + "\n").encode("utf-8") if keys else b""
    vals_b = ("\n".join(vals) + "\n").encode("utf-8") if vals else b""
    manifest = {
        "format": SNAP_FORMAT,
        "topology_group": group,
        "gen": gen,
        "shard": int(shard),
        "num_shards": int(num_shards),
        "offset": int(offset),
        "rows": len(keys),
        "checksum": _columns_checksum(keys_b, vals_b),
        "ts": time.time(),
    }
    name = f"snap-{num_shards}-{shard}-{offset}-{time.time_ns()}"
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, f".tmp-{name}-{os.getpid()}")
    os.makedirs(tmp, exist_ok=True)
    for fname, data in ((_KEYS, keys_b), (_VALS, vals_b)):
        with open(os.path.join(tmp, fname), "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    final = os.path.join(root, name)
    os.rename(tmp, final)
    manifest["path"] = final
    _register(manifest, topic=topic)
    _prune(root, num_shards, shard, keep=snapshot_keep() if keep is None
           else keep)
    return manifest


def _publish_arena(
    root: str,
    table,
    offset: int,
    *,
    shard: int,
    num_shards: int,
    group: Optional[str],
    gen: Optional[int],
    topic: Optional[str],
    keep: Optional[int],
) -> dict:
    """Quiesce-and-copy publish: the arena file IS the artifact.  Same
    crash-safe tmp-dir + rename dance as the columnar writer; the copy is
    a reflink where the filesystem supports it (O(1)), else a hole-aware
    extent copy (O(resident))."""
    t0 = time.monotonic()
    name = f"snap-{num_shards}-{shard}-{offset}-{time.time_ns()}"
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, f".tmp-{name}-{os.getpid()}")
    os.makedirs(tmp, exist_ok=True)
    geom = table.quiesce_copy(os.path.join(tmp, _ARENA))
    manifest = {
        "format": ARENA_FORMAT,
        "topology_group": group,
        "gen": gen,
        "shard": int(shard),
        "num_shards": int(num_shards),
        "offset": int(offset),
        "rows": int(geom["rows"]),
        # no content checksum: rows are seqlock-framed/self-describing and
        # the loader verifies the decoded row count against ``rows``
        "checksum": 0,
        "arena": geom,
        "ts": time.time(),
    }
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    final = os.path.join(root, name)
    os.rename(tmp, final)
    manifest["path"] = final
    try:
        from ..obs.metrics import get_registry

        get_registry().gauge("tpums_arena_publish_seconds").set(
            time.monotonic() - t0)
    except Exception:
        pass
    _register(manifest, topic=topic)
    _prune(root, num_shards, shard, keep=snapshot_keep() if keep is None
           else keep)
    return manifest


def _register(manifest: dict, topic: Optional[str]) -> None:
    """Best-effort ``kind="snapshot"`` registry record for fleet
    observability (the bootstrap path resolves from the data dirs, which
    survive a wiped registry)."""
    try:
        from . import registry

        registry.publish_snapshot(
            registry.snapshot_scope(
                manifest.get("topology_group"), topic,
                manifest["num_shards"], manifest["shard"],
            ),
            manifest,
        )
    except Exception:
        pass


def _prune(root: str, num_shards: int, shard: int, keep: int) -> None:
    import shutil

    all_ms = list_manifests(root)  # oldest-first
    mine = [
        m for m in all_ms
        if m["num_shards"] == num_shards and m["shard"] == shard
    ]
    mine.sort(key=lambda m: (m["offset"], m["ts"]))
    removed = set()
    for old in mine[:-keep]:
        shutil.rmtree(old["path"], ignore_errors=True)
        removed.add(old["path"])
    # foreign-topology leftovers: after an elastic reshard nobody publishes
    # under the OLD num_shards anymore, so its family would outlive every
    # identity-scoped prune above — unbounded growth across reshards.
    # Once a COMPLETE family of the publisher's (current) topology exists,
    # any foreign snapshot at or below that family's replay offset is
    # strictly superseded for every bootstrapper (exact or resharded:
    # resolve() always prefers the higher-offset plan) — reclaim it.
    newest_cur: dict = {}
    for m in all_ms:
        if m["num_shards"] == num_shards and m["path"] not in removed:
            newest_cur[m["shard"]] = m  # oldest-first scan: newest wins
    if set(newest_cur.keys()) < set(range(num_shards)):
        return
    floor = min(m["offset"] for m in newest_cur.values())
    for m in all_ms:
        if m["num_shards"] != num_shards and m["offset"] <= floor:
            shutil.rmtree(m["path"], ignore_errors=True)


# -- discovery / verification ------------------------------------------------

def list_manifests(root: str) -> List[dict]:
    """Well-formed manifests under ``root`` (each with its ``path``),
    oldest-first by offset.  Unreadable or misshapen entries are skipped —
    checksum verification happens at load time, not here."""
    out: List[dict] = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return out
    for name in names:
        if not name.startswith("snap-"):
            continue
        path = os.path.join(root, name)
        try:
            with open(os.path.join(path, _MANIFEST)) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(m, dict) or m.get("format") not in (
                SNAP_FORMAT, ARENA_FORMAT):
            continue
        try:
            m["offset"] = int(m["offset"])
            m["shard"] = int(m["shard"])
            m["num_shards"] = int(m["num_shards"])
            m["rows"] = int(m["rows"])
            m["checksum"] = int(m["checksum"])
        except (KeyError, TypeError, ValueError):
            continue
        m["path"] = path
        out.append(m)
    out.sort(key=lambda m: (m["offset"], m.get("ts", 0.0)))
    return out


def read_columns(manifest: dict) -> Tuple[List[str], List[str]]:
    """Read and VERIFY one snapshot's column files; raises
    ``SnapshotCorruptError`` on checksum/shape mismatch.  An ``arena/1``
    member decodes its seqlock-framed rows (self-describing; verification
    is the decoded row count) — the loader downstream is format-blind."""
    path = manifest["path"]
    if manifest.get("format") == ARENA_FORMAT:
        from .arena import iter_arena_file

        keys = []
        vals = []
        try:
            for k, v in iter_arena_file(os.path.join(path, _ARENA)):
                keys.append(k)
                vals.append(v)
        except (OSError, ValueError) as e:
            raise SnapshotCorruptError(path, f"unreadable arena: {e}")
        # a link-published member shares the live inode: upserts after
        # publish may ADD rows (never remove — LWW, no deletes), so the
        # structural floor is >=; copy members are point-in-time, ==
        linked = (manifest.get("arena") or {}).get("publish") == "link"
        ok = (len(keys) >= manifest["rows"] if linked
              else len(keys) == manifest["rows"])
        if not ok:
            raise SnapshotCorruptError(
                path,
                f"row count mismatch: {len(keys)} decoded, manifest says "
                f"{manifest['rows']}",
            )
        return keys, vals
    try:
        with open(os.path.join(path, _KEYS), "rb") as f:
            keys_b = f.read()
        with open(os.path.join(path, _VALS), "rb") as f:
            vals_b = f.read()
    except OSError as e:
        raise SnapshotCorruptError(path, f"unreadable columns: {e}")
    if _columns_checksum(keys_b, vals_b) != manifest["checksum"]:
        raise SnapshotCorruptError(path, "checksum mismatch")
    # exact mirror of the writer's '"\n".join(col) + "\n"' encoding: split
    # on \n ONLY and drop the one trailing empty element.  splitlines()
    # would also break on \x85/\u2028/\u2029/\v/\f, which are legal INSIDE
    # a key or value (the ingest paths split raw bytes on \n alone) — that
    # skew fails the row-count check below on every restore
    keys = keys_b.decode("utf-8").split("\n")[:-1] if keys_b else []
    vals = vals_b.decode("utf-8").split("\n")[:-1] if vals_b else []
    if len(keys) != len(vals) or len(keys) != manifest["rows"]:
        raise SnapshotCorruptError(
            path,
            f"row count mismatch: {len(keys)} keys / {len(vals)} values, "
            f"manifest says {manifest['rows']}",
        )
    return keys, vals


# -- bootstrap resolution ----------------------------------------------------

def resolve(
    root: str,
    *,
    owner: Optional[Tuple[int, int]] = None,
    min_offset: Optional[int] = None,
    max_offset: Optional[int] = None,
    exclude: Sequence[str] = (),
) -> Optional[dict]:
    """Pick the best bootstrap plan: ``{"offset", "members", "exact"}``.

    ``owner`` is the bootstrapping worker's ``(shard, num_shards)``;
    ``exclude`` holds snapshot paths already found corrupt (the fallback
    chain).  Returns None when nothing usable exists — the caller falls
    back to full journal replay."""
    ms = [
        m for m in list_manifests(root)
        if m["path"] not in exclude
        and (min_offset is None or m["offset"] >= min_offset)
        and (max_offset is None or m["offset"] <= max_offset)
    ]
    if not ms:
        return None
    candidates: List[dict] = []
    if owner is not None:
        shard, num_shards = owner
        exact = [
            m for m in ms
            if m["num_shards"] == num_shards and m["shard"] == shard
        ]
        if exact:
            best = exact[-1]  # list_manifests sorts oldest-first
            candidates.append(
                {"offset": best["offset"], "members": [best], "exact": True}
            )
    # complete families: one (latest) member per shard of a source N.
    # Needed when the worker's sharding differs (or no owner was given) —
    # covering the whole key space takes all N source slices.
    by_n: dict = {}
    for m in ms:
        by_n.setdefault(m["num_shards"], {})[m["shard"]] = m  # newest wins
    for n, shards in by_n.items():
        if set(shards.keys()) != set(range(n)):
            continue
        members = [shards[s] for s in range(n)]
        candidates.append(
            {
                "offset": min(m["offset"] for m in members),
                "members": members,
                "exact": False,
            }
        )
    if not candidates:
        return None
    # highest replay-from offset wins; an exact-identity plan beats a
    # family at the same offset (one file, no filtering)
    candidates.sort(key=lambda p: (p["offset"], p["exact"]))
    return candidates[-1]


def load_plan(
    table,
    plan: dict,
    *,
    owner: Optional[Tuple[int, int]] = None,
) -> int:
    """Bulk-load a plan's members through ``put_many_columns``; returns
    rows loaded.  Raises ``SnapshotCorruptError`` on any bad member (the
    caller excludes it and re-resolves — last-writer-wins re-loading makes
    a partially-applied plan harmless)."""
    from .table import _fnv1a_batch

    rows = 0
    for m in plan["members"]:
        keys, vals = read_columns(m)
        if not keys:
            continue
        hashes = None
        if owner is not None and not (
            plan["exact"]
            and m["num_shards"] == owner[1]
            and m["shard"] == owner[0]
        ):
            shard, num_shards = owner
            hashes = _fnv1a_batch(keys)
            mine = hashes % num_shards == shard
            if not bool(mine.all()):
                import numpy as np

                keys = np.asarray(keys, dtype=object)[mine].tolist()
                vals = np.asarray(vals, dtype=object)[mine].tolist()
                hashes = hashes[mine]
        table.put_many_columns(keys, vals, hashes=hashes)
        rows += len(keys)
    return rows


def bootstrap(
    table,
    root: str,
    *,
    owner: Optional[Tuple[int, int]] = None,
    min_offset: Optional[int] = None,
    max_offset: Optional[int] = None,
    on_corrupt: Optional[Callable[[dict], None]] = None,
) -> Optional[dict]:
    """The full fallback chain: newest valid snapshot -> older snapshot ->
    None (caller replays the journal).  Returns
    ``{"offset", "rows", "members", "age_s"}`` on success."""
    exclude: set = set()
    while True:
        plan = resolve(
            root, owner=owner, min_offset=min_offset,
            max_offset=max_offset, exclude=exclude,
        )
        if plan is None:
            return None
        try:
            rows = load_plan(table, plan, owner=owner)
        except SnapshotCorruptError as e:
            bad = next(
                (m for m in plan["members"] if m["path"] == e.path),
                plan["members"][0],
            )
            exclude.add(bad["path"])
            if on_corrupt is not None:
                try:
                    on_corrupt(bad)
                except Exception:
                    pass
            print(f"[snapshot] {e}; trying older", file=sys.stderr)
            continue
        newest_ts = max(
            (m.get("ts", 0.0) for m in plan["members"]), default=0.0
        )
        return {
            "offset": plan["offset"],
            "rows": rows,
            "members": len(plan["members"]),
            "exact": plan["exact"],
            "age_s": max(time.time() - newest_ts, 0.0) if newest_ts else None,
        }
