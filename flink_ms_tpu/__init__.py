"""flink_ms_tpu — a TPU-native framework with the capabilities of mmziyad/flink-ms.

Not a port: the reference's Flink DataSet/DataStream pipelines are re-designed as
sharded JAX arrays on a TPU mesh (pjit/shard_map + XLA collectives), and its
queryable-state serving layer as a device-resident sharded model table behind a
host lookup server. See SURVEY.md for the structural analysis of the reference
and the layer-by-layer parity map.

Package layout
--------------
core/      flags (ParameterTool-parity parser), text-format contracts, IO
parallel/  device mesh bootstrap, sharding helpers
ops/       numerical kernels: blocked ALS, CoCoA/SDCA SVM, online SGD math
train/     training CLIs (als_train, svm_train) — parity with ALSImpl/SVMImpl
serve/     sharded model table, ingest journal, state backends, lookup server
online/    streaming online-SGD updater (closes the loop into serving)
eval/      MSE evaluator, mean-vector job
gen/       synthetic model generators
client/    predict REPLs + random-load latency harnesses
utils/     logging, misc
"""

__version__ = "0.1.0"
