"""Mean factor-vector job — counterpart of ``ALSMeanVector``
(``flink-als/src/main/scala/de/tub/it4bi/ALSMeanVector.scala``).

Computes the elementwise mean of all factor vectors in a model file and emits
the cold-start row ``MEAN,U|I,f1;...`` consumed by the serving layer and the
online SGD updater (SGD.java:142-151 falls back to these rows for unseen
users/items).
"""

from __future__ import annotations

import sys

import numpy as np

from ..core import formats as F
from ..core.params import Params


def run(params: Params) -> str | None:
    type_flag = params.get_required("type")
    if type_flag == "item":
        factor_type = F.ITEM
    elif type_flag == "user":
        factor_type = F.USER
    else:
        raise ValueError("specify type as either 'item' or 'user'.")

    _ids, _types, factors = F.read_als_model(params.get_required("input"))
    if factors.size == 0:
        raise ValueError("empty model input")
    mean = np.mean(factors, axis=0)
    row = F.format_mean_row(factor_type, mean)

    if params.has("output"):
        F.write_lines(params.get_required("output"), [row])
    else:
        print("Printing results to stdout. Use --output to specify output location")
        print(row)
    return row


def main(argv=None) -> None:
    run(Params.from_args(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    main()
