"""MSE evaluator — counterpart of ``MSE``
(``als-ms/src/main/java/de/tub/it4bi/modelserving/evaluation/MSE.java``).

Evaluates mean squared error of a ratings set against an ALS model.  Two
sources, matching the reference's deployment shape plus an offline mode:

- **live** (reference parity): queries the serving layer one user per group
  and one item per rating (MSE.java:122-159) through the query client —
  flags ``--jobId --jobManagerHost --jobManagerPort --queryTimeout``.
- **offline** (``--model path[,path...]``): reads model row files directly
  and computes predictions as one batched device op.

Skip semantics preserved from the reference: a missing user drops that
user's whole group (MSE.java:137-139 ``break``), a missing item drops just
that rating (:156-158) — minus the reference's NPE ordering bug (SURVEY.md
Appendix C #7).  Input CSV always skips the first line (MSE.java:43
``ignoreFirstLine()``).
"""

from __future__ import annotations

import sys
from typing import Dict, Optional, Tuple

import numpy as np

from ..core import formats as F
from ..core.params import Params, field_delimiter_from


def _load_model_tables(paths: str) -> Dict[str, np.ndarray]:
    """Read ALS rows from comma-separated paths into a {key: factors} map
    keyed like the serving state: ``"<id>-U"`` / ``"<id>-I"``
    (ALSKafkaConsumer.java:75-82)."""
    table: Dict[str, np.ndarray] = {}
    for path in paths.split(","):
        for line in F.iter_lines(path):
            id_, typ, vec = F.parse_als_row(line)
            table[f"{id_}-{typ}"] = vec
    return table


def rolling_holdout_split(
    users,
    items,
    ratings,
    *,
    fraction: float = 0.2,
    seed: int = 0,
    min_train_per_user: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Seeded, user-stratified held-out split -> (train_idx, holdout_idx).

    The autopilot's evaluation slice: per user with enough ratings,
    ``fraction`` of them (at least one, never more than leaves
    ``min_train_per_user`` behind) move to the held-out side; users with
    too few ratings keep everything in train.  Stratifying per user
    guarantees every held-out user has train-side ratings — without it,
    ``compute_mse``'s reference skip semantics (a missing user drops its
    whole group) would silently evaluate nothing for users the candidate
    model never trained on, and the candidate-vs-incumbent comparison
    would reward models that forget users.

    Deterministic in (inputs, seed): same triples and seed -> identical
    index arrays, so the incumbent and every candidate are scored on the
    byte-identical slice.  Rolling windows pass ``seed=base + version``
    to rotate which ratings are held out as the window grows.

    Returns positional indices into the input arrays (both sorted
    ascending, disjoint, covering every row).
    """
    users = np.asarray(users)
    n = len(users)
    if not 0.0 < fraction < 1.0:
        raise ValueError(f"fraction must be in (0, 1), got {fraction}")
    if len(np.asarray(items)) != n or len(np.asarray(ratings)) != n:
        raise ValueError("users/items/ratings length mismatch")
    rng = np.random.default_rng(seed)
    holdout: list = []
    order = np.argsort(users, kind="stable")
    sorted_users = users[order]
    # group boundaries over the stable sort: per-user index runs, visited
    # in ascending user order so the rng consumption is input-order
    # independent for a fixed triple set
    starts = np.flatnonzero(
        np.r_[True, sorted_users[1:] != sorted_users[:-1]])
    ends = np.r_[starts[1:], n]
    for s, e in zip(starts, ends):
        grp = order[s:e]
        n_grp = len(grp)
        n_hold = min(max(int(round(fraction * n_grp)), 1),
                     n_grp - min_train_per_user)
        if n_hold <= 0:
            continue
        holdout.extend(rng.choice(grp, size=n_hold, replace=False).tolist())
    holdout_idx = np.sort(np.asarray(holdout, dtype=np.int64))
    mask = np.ones(n, dtype=bool)
    mask[holdout_idx] = False
    return np.flatnonzero(mask), holdout_idx


def compute_mse(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    lookup,
    lookup_many=None,
) -> Tuple[Optional[float], int, int]:
    """Reference group/skip semantics over an arbitrary key->factors lookup.

    ``lookup_many`` (optional): batched variant taking a list of keys and
    returning payload-or-None per key.  When given, each user group costs
    ONE round trip (user + all its items in a single MGET) vs the
    reference's one-per-group plus one-per-rating (MSE.java:129-158).
    Skip semantics are unchanged: a missing user still drops the whole
    group, a missing item just its rating.

    Returns (mse | None if nothing scored, n_scored, n_skipped).
    """
    sq_sum = 0.0
    n_scored = 0
    n_skipped = 0
    for u in np.unique(users):
        sel = users == u
        group_items = items[sel]
        group_ratings = ratings[sel]
        if lookup_many is not None:
            keys = [f"{u}-U"] + [f"{it}-I" for it in group_items]
            payloads = lookup_many(keys)
            uf = payloads[0]
            item_payloads = payloads[1:]
        else:
            uf = lookup(f"{u}-U")
            item_payloads = None
        if uf is None:
            print(f"No record found for the user ID: {u}-U", file=sys.stderr)
            n_skipped += int(sel.sum())
            continue
        for j, (it, r) in enumerate(zip(group_items, group_ratings)):
            itf = item_payloads[j] if item_payloads is not None else lookup(f"{it}-I")
            if itf is None:
                print(
                    f"No record found for the itemID query: {it}-I", file=sys.stderr
                )
                n_skipped += 1
                continue
            pred = float(np.dot(uf, itf))
            sq_sum += (r - pred) ** 2
            n_scored += 1
    return (sq_sum / n_scored if n_scored else None), n_scored, n_skipped


def _compute_mse_offline_batched(
    users, items, ratings, table: Dict[str, np.ndarray]
) -> Tuple[Optional[float], int, int]:
    """Same semantics as compute_mse, but predictions in one device op."""
    from ..ops.als import ALSModel, predict
    from ..parallel.mesh import honor_platform_env

    honor_platform_env()  # explicit JAX_PLATFORMS pin must reach the device op

    def numeric_ids(suffix: str):
        out = set()
        for key in table:
            if key.endswith(suffix):
                id_part = key[: -len(suffix)]
                # model dumps legitimately contain the MEAN cold-start row
                # (ALSMeanVector.scala:35); only numeric ids are scoreable
                if id_part.lstrip("-").isdigit():
                    out.add(int(id_part))
        return sorted(out)

    u_ids = numeric_ids("-U")
    i_ids = numeric_ids("-I")
    if not u_ids or not i_ids:
        return None, 0, len(ratings)
    uf = np.stack([table[f"{u}-U"] for u in u_ids])
    itf = np.stack([table[f"{i}-I"] for i in i_ids])
    model = ALSModel(
        user_ids=np.asarray(u_ids),
        item_ids=np.asarray(i_ids),
        user_factors=uf,
        item_factors=itf,
    )
    known_u = np.isin(users, model.user_ids)
    known_i = np.isin(items, model.item_ids)
    ok = known_u & known_i
    preds = predict(model, users[ok], items[ok])
    err = ratings[ok] - preds
    n_scored = int(ok.sum())
    return (
        (float(np.mean(err * err)) if n_scored else None),
        n_scored,
        int((~ok).sum()),
    )


def run(params: Params, lookup=None) -> Optional[float]:
    delim = field_delimiter_from(params, default="tab")
    users, items, ratings = F.read_ratings(
        params.get_required("input"), field_delimiter=delim, ignore_first_line=True
    )

    if params.has("model"):
        table = _load_model_tables(params.get_required("model"))
        mse, n_scored, n_skipped = _compute_mse_offline_batched(
            users, items, ratings, table
        )
    else:
        lookup_many = None
        if lookup is None:
            from ..serve.client import QueryClient

            from ..serve.registry import resolve_endpoint

            mse_host, mse_port = resolve_endpoint(params)
            client = QueryClient(
                host=mse_host,
                port=mse_port,
                timeout_s=params.get_int("queryTimeout", 5),
            )

            def _parse(payload):
                if payload is None:
                    return None
                # serving values are the factor payload "f1;f2;..."
                return np.asarray([float(t) for t in payload.split(";") if t])

            def lookup(key: str):
                return _parse(client.query_state("ALS_MODEL", key))

            if params.get_bool("batchedLookups", True):
                # one MGET round trip per user group (vs one per rating)
                def lookup_many(keys):
                    return [
                        _parse(p)
                        for p in client.query_states("ALS_MODEL", keys)
                    ]

        mse, n_scored, n_skipped = compute_mse(
            users, items, ratings, lookup, lookup_many=lookup_many
        )

    if n_skipped:
        print(f"skipped {n_skipped} ratings with missing keys", file=sys.stderr)
    if mse is None:
        print("No predictions could be made (empty model?)", file=sys.stderr)
        return None
    if params.has("output"):
        F.write_lines(params.get_required("output"), [repr(float(mse))])
    else:
        print("Printing result to stdout. Use --output to specify output path.")
        print(mse)
    return mse


def main(argv=None) -> None:
    run(Params.from_args(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    main()
