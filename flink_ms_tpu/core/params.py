"""Flag parsing with the semantics of Flink's ``ParameterTool.fromArgs``.

Every entry point in the reference parses flags via
``ParameterTool.fromArgs(args)`` (e.g. ``ALSImpl.scala:18``, ``SGD.java:40``,
``MSE.java:36``).  This module reproduces those semantics so the new
framework's CLIs accept the exact flag inventory in SURVEY.md Appendix A:

- flags are ``--key value`` or ``-key value``
- a flag followed by another flag (or end of argv) is a valueless boolean flag
- ``get*`` accessors with defaults, ``getRequired`` raising on absence
- unknown flags are carried, not rejected (Flink passes them through to e.g.
  Kafka properties — ``ALSKafkaConsumer.java:70``)
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

_NO_VALUE = "__NO_VALUE_KEY"


class Params:
    """Immutable-ish key/value flag map (ParameterTool parity)."""

    def __init__(self, data: Dict[str, str]):
        self._data = dict(data)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_args(cls, args: Iterable[str]) -> "Params":
        data: Dict[str, str] = {}
        toks: List[str] = list(args)
        i = 0
        while i < len(toks):
            tok = toks[i]
            if tok.startswith("--"):
                key = tok[2:]
            elif tok.startswith("-") and not _is_number(tok):
                key = tok[1:]
            else:
                raise ValueError(
                    f"Error parsing arguments '{toks}' on '{tok}'. "
                    "Please prefix keys with -- or -."
                )
            if not key:
                raise ValueError("The input " + str(toks) + " contains an empty argument")
            i += 1
            if i >= len(toks):
                data[key] = _NO_VALUE
            else:
                nxt = toks[i]
                if nxt.startswith("-") and not _is_number(nxt):
                    data[key] = _NO_VALUE
                else:
                    data[key] = nxt
                    i += 1
        return cls(data)

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Params":
        return cls({k: str(v) for k, v in d.items()})

    # -- accessors ---------------------------------------------------------

    def has(self, key: str) -> bool:
        return key in self._data

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self._data.get(key)
        if v is None or v == _NO_VALUE:
            return default
        return v

    def get_required(self, key: str) -> str:
        if key not in self._data:
            raise KeyError(f"No data for required key '{key}'")
        v = self._data[key]
        if v == _NO_VALUE:
            raise ValueError(f"The argument for required key '{key}' is missing")
        return v

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        v = self.get(key)
        return int(v) if v is not None else default

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        v = self.get(key)
        return float(v) if v is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._data.get(key)
        if v is None:
            return default
        if v == _NO_VALUE:
            # bare `--partition` style flag counts as true (ParameterTool
            # returns the default there; the reference always passes a value,
            # so treating bare presence as true is a strict superset)
            return True
        return v.strip().lower() in ("true", "1", "yes")

    def to_dict(self) -> Dict[str, str]:
        return dict(self._data)

    def properties(self, prefix: str = "") -> Dict[str, str]:
        """All flags (optionally filtered by prefix) as a properties dict —
        the analog of ``parameterTool.getProperties()`` passed to Kafka at
        ``ALSKafkaConsumer.java:70``."""
        out = {}
        for k, v in self._data.items():
            if k.startswith(prefix) and v != _NO_VALUE:
                out[k] = v
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"Params({self._data!r})"


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def field_delimiter_from(params: Params, default: str = "comma") -> str:
    """Map the reference's ``--fieldDelimiter comma|tab`` convention
    (``ALSImpl.scala:22-26``) to the actual character.  Raw one-char
    delimiters are also accepted."""
    v = params.get("fieldDelimiter", default)
    if v == "comma":
        return ","
    if v == "tab":
        return "\t"
    if len(v) == 1:
        return v
    raise ValueError(f"unsupported fieldDelimiter: {v!r} (use comma|tab)")
