"""Text-format contracts shared with the reference (SURVEY.md Appendix B).

These formats are the interop boundary: model files written by this framework
are byte-compatible row-wise with the reference's, so the reference's Kafka
loaders / clients could consume them unchanged and vice versa.

| format                    | shape                              | reference          |
|---------------------------|------------------------------------|--------------------|
| ratings CSV               | ``user,item,rating`` (comma/tab)   | ALSImpl.scala:29-32|
| LibSVM                    | ``label idx:val ...`` (1-based)    | SVMImpl.scala:21   |
| ALS model row             | ``id,U|I,f1;f2;...;fk``            | ALSImpl.scala:83-85|
| ALS mean row              | ``MEAN,U|I,f1;...``                | ALSMeanVector.scala:35 |
| SVM model row (flat)      | ``featureIndex,weight`` (1-based)  | SVMImpl.scala:33-35|
| SVM model row (ranged)    | ``bucket,idx:w;idx:w;...``         | SVMImpl.scala:63-71|
| latency CSV (ALS)         | ``uId,iId,prediction,ms``          | ALSPredictRandom.java:94 |
| latency CSV (SVM)         | ``qId,nFeatures,prediction,ms``    | SVMPredictRandom.java:91 |

All readers accept a file path or a directory (Flink jobs with parallelism > 1
write directories of part files; the reference's Kafka producers enumerate
nested dirs — ``ALSKafkaProducer.java:24-26``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

USER = "U"
ITEM = "I"
MEAN_ID = "MEAN"


# ---------------------------------------------------------------------------
# generic line IO (file-or-directory)
# ---------------------------------------------------------------------------

def iter_lines(path: str) -> Iterator[str]:
    """Yield non-empty lines from a file, or from every file under a
    directory (recursive, sorted for determinism)."""
    for fp in _enumerate_files(path):
        with open(fp, "r") as f:
            for line in f:
                line = line.rstrip("\n").rstrip("\r")
                if line:
                    yield line


def _enumerate_files(path: str) -> List[str]:
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            for name in files:
                if name.startswith(".") or name.startswith("_"):
                    continue
                out.append(os.path.join(root, name))
        return sorted(out)
    return [path]


def write_lines(path: str, lines: Iterable[str]) -> None:
    """Overwrite `path` with the given lines (WriteMode.OVERWRITE parity)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        for line in lines:
            f.write(line)
            f.write("\n")


# ---------------------------------------------------------------------------
# ratings CSV
# ---------------------------------------------------------------------------

def read_ratings(
    path: str,
    field_delimiter: str = ",",
    ignore_first_line: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Read ``user,item,rating`` rows -> (users:int64, items:int64, ratings:f64).

    Mirrors ``env.readCsvFile[(Int, Int, Double)]`` at ALSImpl.scala:29-32
    (comma or tab delimiter, optional header skip).
    """
    users: List[int] = []
    items: List[int] = []
    ratings: List[float] = []
    for fp in _enumerate_files(path):
        with open(fp, "r") as f:
            # Flink's CsvInputFormat skips the first line of EVERY file when
            # ignoreFirstLine is set (each split re-skips at splitStart==0)
            skip = ignore_first_line
            for line in f:
                if skip:
                    skip = False
                    continue
                line = line.strip()
                if not line:
                    continue
                parts = line.split(field_delimiter)
                users.append(int(parts[0]))
                items.append(int(parts[1]))
                ratings.append(float(parts[2]))
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(ratings, dtype=np.float64),
    )


def write_ratings(
    path: str,
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    field_delimiter: str = ",",
) -> None:
    write_lines(
        path,
        (
            f"{int(u)}{field_delimiter}{int(i)}{field_delimiter}{_fmt(r)}"
            for u, i, r in zip(users, items, ratings)
        ),
    )


# ---------------------------------------------------------------------------
# LibSVM
# ---------------------------------------------------------------------------

@dataclass
class SparseData:
    """CSR sparse labeled data parsed from LibSVM (indices stored 0-based)."""

    labels: np.ndarray      # (n,) float64
    indptr: np.ndarray      # (n+1,) int64
    indices: np.ndarray     # (nnz,) int64, 0-based
    values: np.ndarray      # (nnz,) float64
    n_features: int

    @property
    def n_examples(self) -> int:
        return int(self.labels.shape[0])

    def row(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        s, e = self.indptr[i], self.indptr[i + 1]
        return self.indices[s:e], self.values[s:e]


def read_libsvm(path: str, n_features: int = 0) -> SparseData:
    """Parse LibSVM ``label idx:val ...`` with 1-based indices
    (``env.readLibSVM`` at SVMImpl.scala:21 [dep])."""
    labels: List[float] = []
    indptr: List[int] = [0]
    indices: List[int] = []
    values: List[float] = []
    max_idx = -1
    for line in iter_lines(path):
        # strip LibSVM comments
        hash_pos = line.find("#")
        if hash_pos >= 0:
            line = line[:hash_pos]
        parts = line.split()
        if not parts:
            continue
        labels.append(float(parts[0]))
        for tok in parts[1:]:
            idx_s, val_s = tok.split(":")
            idx = int(idx_s) - 1  # 1-based on disk -> 0-based in memory
            if idx < 0:
                raise ValueError(f"LibSVM index must be >= 1, got {idx + 1}")
            indices.append(idx)
            values.append(float(val_s))
            if idx > max_idx:
                max_idx = idx
        indptr.append(len(indices))
    nf = max(n_features, max_idx + 1)
    return SparseData(
        labels=np.asarray(labels, dtype=np.float64),
        indptr=np.asarray(indptr, dtype=np.int64),
        indices=np.asarray(indices, dtype=np.int64),
        values=np.asarray(values, dtype=np.float64),
        n_features=nf,
    )


# ---------------------------------------------------------------------------
# ALS model rows:  id,U|I,f1;f2;...;fk
# ---------------------------------------------------------------------------

def format_als_row(id_: object, factor_type: str, factors: Sequence[float]) -> str:
    """``OutputFactor.toString`` parity (ALSImpl.scala:83-85).

    ``tolist`` first: iterating a numpy row boxes one array scalar per
    element (~3x the repr cost itself) — this formatter is the online-SGD
    emit hot path."""
    if isinstance(factors, np.ndarray):
        factors = factors.tolist()
    return f"{id_},{factor_type},{';'.join([_fmt(f) for f in factors])}"


def parse_als_row(line: str) -> Tuple[str, str, np.ndarray]:
    """Parse ``id,U|I,f1;f2;...`` -> (id, type, factors).  Id kept as a string
    because the serving key space is stringly typed ("MEAN" included) —
    ALSKafkaConsumer.java:75-82."""
    id_, typ, payload = line.split(",", 2)
    return id_, typ, np.asarray(
        [float(t) for t in _split_semis(payload)], dtype=np.float64
    )


def write_als_model(path: str, ids: Sequence[object], factor_type: str,
                    factors: np.ndarray) -> None:
    write_lines(
        path,
        (format_als_row(i, factor_type, row) for i, row in zip(ids, np.asarray(factors))),
    )


def read_als_model(path: str) -> Tuple[List[str], List[str], np.ndarray]:
    """Read a model file/dir -> (ids, types, factors matrix).  All rows must
    share one factor dimensionality."""
    ids: List[str] = []
    types: List[str] = []
    rows: List[np.ndarray] = []
    for line in iter_lines(path):
        i, t, v = parse_als_row(line)
        ids.append(i)
        types.append(t)
        rows.append(v)
    if not rows:
        return [], [], np.zeros((0, 0), dtype=np.float64)
    return ids, types, np.stack(rows)


def format_mean_row(factor_type: str, mean: Sequence[float]) -> str:
    """``MEAN,U|I,f1;...`` (ALSMeanVector.scala:35)."""
    return format_als_row(MEAN_ID, factor_type, mean)


# ---------------------------------------------------------------------------
# SVM model rows
# ---------------------------------------------------------------------------

def format_svm_flat_rows(weights: np.ndarray) -> Iterator[str]:
    """``featureIndex,weight`` with 1-based indices (SVMImpl.scala:33-35,45)."""
    for i, w in enumerate(np.asarray(weights).ravel()):
        yield f"{i + 1},{_fmt(w)}"


def format_svm_range_rows(weights: np.ndarray, range_: int) -> Iterator[str]:
    """``bucket,idx:w;idx:w;...`` with bucket = (1-based idx) / range
    (SVMImpl.scala:40-46,63-71).  Buckets emitted in ascending order; indices
    within a bucket ascend (the reference's groupBy preserves none of this,
    but deterministic order simplifies testing and diffing)."""
    w = np.asarray(weights).ravel()
    buckets: Dict[int, List[str]] = {}
    for i, v in enumerate(w):
        idx1 = i + 1
        buckets.setdefault(idx1 // range_, []).append(f"{idx1}:{_fmt(v)}")
    for b in sorted(buckets):
        yield f"{b}," + ";".join(buckets[b])


def parse_svm_flat_row(line: str) -> Tuple[int, float]:
    idx_s, w_s = line.split(",", 1)
    return int(idx_s), float(w_s)


def parse_svm_range_row(line: str) -> Tuple[int, List[Tuple[int, float]]]:
    """Parse ``bucket,idx:w;idx:w;...`` (RangePartitionSVMPredict.java:80-101)."""
    bucket_s, payload = line.split(",", 1)
    idx, w = parse_svm_range_payload(payload)
    return int(bucket_s), list(zip(idx.tolist(), w.tolist()))


def sort_dedup_last(idx: np.ndarray, w: np.ndarray) -> Tuple[np.ndarray,
                                                             np.ndarray]:
    """Ascending-sort (idx, w) pairs, resolving duplicate ids LAST-wins —
    the dict-based parse semantics every range-plane consumer has (stable
    sort keeps input order within a run of equal ids, so the last element
    of each run is the last occurrence)."""
    order = np.argsort(idx, kind="stable")
    si, sw = idx[order], w[order]
    if si.size:
        keep = np.concatenate([si[1:] != si[:-1], [True]])
        si, sw = si[keep], sw[keep]
    return si, sw


def gather_sorted(ref_idx: np.ndarray, ref_w: np.ndarray,
                  fids) -> Tuple[np.ndarray, np.ndarray]:
    """Weights for `fids` out of an ascending (ref_idx, ref_w) table.

    -> (weights aligned with ``fids``, boolean hit mask); misses carry
    weight 0.  One place owns the clamp-then-mask searchsorted subtlety
    for every range-plane consumer (client cache, DOT merged index)."""
    fa = np.asarray(fids, np.int64)
    if ref_idx.size == 0 or fa.size == 0:
        return np.zeros(fa.size, np.float64), np.zeros(fa.size, bool)
    pos = np.minimum(np.searchsorted(ref_idx, fa), ref_idx.size - 1)
    hit = ref_idx[pos] == fa
    out = np.where(hit, ref_w[pos], 0.0)
    return out, hit


class RangePayloadCache:
    """Payload-keyed cache of parsed+sorted range rows.

    A range-partitioned query touches most buckets every time (70 random
    features over ~48 buckets), and bucket payloads change only when the
    model is republished — so the ~0.3 ms C-parse of a ~2000-token payload
    dominates steady-state query latency.  Keying on the payload STRING
    (not the bucket id) makes the cache trivially coherent: a republished
    bucket arrives as a different string and misses.  Bounded FIFO;
    thread-safe (the DOT merged-index rebuild runs on server handler
    threads, any number of which may share one cache)."""

    def __init__(self, max_entries: int = 1024):
        import threading

        self.max_entries = max_entries
        self._cache: dict = {}
        self._lock = threading.Lock()

    def lookup(self, payload: str) -> Tuple[np.ndarray, np.ndarray]:
        """-> (ascending index array, matching weight array)."""
        with self._lock:
            hit = self._cache.get(payload)
        if hit is not None:
            return hit
        entry = sort_dedup_last(*parse_svm_range_payload(payload))
        with self._lock:
            while len(self._cache) >= self.max_entries and self._cache:
                self._cache.pop(next(iter(self._cache)))
            self._cache[payload] = entry
        return entry

    def gather(self, payload: str, fids) -> Tuple[np.ndarray, np.ndarray]:
        """Weights for the requested feature ids (see gather_sorted)."""
        ref_idx, ref_w = self.lookup(payload)
        return gather_sorted(ref_idx, ref_w, fids)


def parse_svm_range_payload(payload: str) -> Tuple[np.ndarray, np.ndarray]:
    """``idx:w;idx:w;...`` -> (int index array, float weight array).

    Fast path parses the whole payload with numpy's C float parser (the
    range-serving client reads ~1000-pair payloads per bucket on every
    query, where per-token ``float()`` dominated the measured latency).
    The ``idx:w;idx:w`` structure is validated EXACTLY first — colon and
    semicolon byte positions must strictly alternate — so a corrupted row
    ("1;2", "1:2:3;4") is never silently re-paired; it takes the per-token
    path and raises there, same as before the fast path existed."""
    stripped = payload.rstrip(";")
    if not stripped:
        return np.zeros(0, np.int64), np.zeros(0, np.float64)
    try:
        buf = np.frombuffer(stripped.encode("ascii"), np.uint8)
        cpos = np.nonzero(buf == ord(":"))[0]
        spos = np.nonzero(buf == ord(";"))[0]
        structured = (
            cpos.size == spos.size + 1
            and (cpos[:-1] < spos).all()
            and (spos < cpos[1:]).all()
        )
        if structured:
            # the index regions must be INTEGER-shaped bytes, not merely
            # integer-valued floats: "3.0:w" or "3e0:w" must fail here and
            # raise on the per-token int() path below, exactly like the
            # exact path always did (ADVICE r2).  Region [start, colon) is
            # clean iff it contains only digits/sign — checked in one
            # cumulative-sum pass, no per-token work.
            digit = (buf >= ord("0")) & (buf <= ord("9"))
            sign = (buf == ord("-")) | (buf == ord("+"))
            bad = np.concatenate([[0], np.cumsum(~(digit | sign))])
            starts = np.concatenate([[0], spos + 1])
            if (bad[cpos] == bad[starts]).all():
                flat = np.array(
                    stripped.replace(":", ";").split(";"), dtype=np.float64
                )
                idx = flat[0::2]
                idx_i = idx.astype(np.int64)
                if (idx_i == idx).all():
                    return idx_i, flat[1::2]
    except Exception:
        pass  # non-ascii / non-numeric: the exact path decides below
    idxs, ws = [], []
    for tok in _split_semis(payload):
        idx_s, w_s = tok.split(":")
        idxs.append(int(idx_s))
        ws.append(float(w_s))
    return np.asarray(idxs, np.int64), np.asarray(ws, np.float64)


def read_svm_model(path: str, n_features: int = 0,
                   partitioned: bool = False) -> np.ndarray:
    """Read flat or range-partitioned SVM rows into a dense 0-based weight
    vector."""
    entries: List[Tuple[int, float]] = []
    for line in iter_lines(path):
        if partitioned:
            _, es = parse_svm_range_row(line)
            entries.extend(es)
        else:
            entries.append(parse_svm_flat_row(line))
    nf = max([n_features] + [i for i, _ in entries])
    w = np.zeros(nf, dtype=np.float64)
    for idx1, v in entries:
        w[idx1 - 1] = v
    return w


# ---------------------------------------------------------------------------
# columnar journal-chunk parsing (the serving ingest hot path)
# ---------------------------------------------------------------------------

# chunk-parse modes, shared with the native bulk-ingest plane
# (tpums_ingest_buf) and the per-row parsers in serve/consumer.py
CHUNK_ALS = 0  # ``id,T,payload``  -> key "id-T", value payload
CHUNK_SVM = 1  # ``key,payload``   -> key raw first token, value rest


def _fnv1a_ranges(buf: "np.ndarray", starts: "np.ndarray",
                  ends: "np.ndarray") -> Optional["np.ndarray"]:
    """Vectorized 32-bit FNV-1a over byte ranges of ``buf`` — the same
    hash ``serve.table._fnv1a`` computes over each key's utf-8 bytes, but
    straight from the chunk buffer: no per-key ``str.encode`` calls.
    Returns None when a range is oversized (caller falls back to the
    per-key path)."""
    n = len(starts)
    if n == 0:
        return np.empty(0, np.uint32)
    lens = (ends - starts).astype(np.int64)
    L = int(lens.max())
    if L > 256:
        return None  # degenerate key; don't build an (n, L) buffer for it
    h = np.full(n, 0x811C9DC5, np.uint32)
    if L == 0:
        return h
    padded = np.zeros((n, L), np.uint8)
    total = int(lens.sum())
    row = np.repeat(np.arange(n), lens)
    col = np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)
    padded[row, col] = buf[np.repeat(starts, lens) + col]
    prime = np.uint32(0x01000193)
    for j in range(L):
        hx = (h ^ padded[:, j]) * prime
        h = np.where(j < lens, hx, h)
    return h


def split_journal_chunk(data: bytes, mode: int, with_hashes: bool = False):
    """Columnar parse of a whole journal byte chunk -> (keys, values,
    parse_errors), or with ``with_hashes`` -> (keys, values, parse_errors,
    hashes) where ``hashes`` is the per-key uint32 FNV-1a array (the shard
    routing hash, computed from the chunk bytes with zero per-key Python
    work) or None when the chunk had degenerate keys.

    The scalar ingest path pays one ``str.split`` + f-string + exception
    frame per row; at 1M-row replays that Python loop IS the ingest
    bottleneck.  This parser instead locates every newline and comma with
    numpy byte scans, rewrites the key/value separators in ONE buffer
    pass, and materializes all key/value strings with a single C-level
    ``str.split`` — per-row Python work is zero.

    Semantics are pinned byte-identical to the per-row parsers
    (``parse_als_record`` / ``parse_svm_record``, tests assert parity):

    - ALS rows need >= 2 commas; the first comma becomes the "-" of the
      ``<id>-<T>`` key, the payload may itself contain commas.  Rows with
      fewer commas count as parse errors (skip-and-count).
    - SVM rows split at the FIRST comma; a row with no comma yields
      (row, "") and is NOT an error (str.partition semantics).
    - empty lines are skipped silently; a trailing "\\r" (CRLF input) is
      stripped like ``str.splitlines`` does.
    """
    if mode not in (CHUNK_ALS, CHUNK_SVM):
        raise ValueError(f"unknown chunk mode: {mode}")
    if not data:
        return ([], [], 0, None) if with_hashes else ([], [], 0)
    if data[-1:] != b"\n":
        data = data + b"\n"  # journal chunks end at a newline; be defensive
    buf = np.frombuffer(data, np.uint8)
    nl = np.nonzero(buf == ord("\n"))[0]
    starts = np.empty_like(nl)
    starts[0] = 0
    starts[1:] = nl[:-1] + 1
    ends = nl.copy()  # exclusive end of line content
    # CRLF tolerance, matching splitlines() on the scalar path
    cr = buf[np.maximum(ends - 1, 0)] == ord("\r")
    cr &= ends > starts
    ends = ends - cr
    nonempty = ends > starts
    cpos = np.nonzero(buf == ord(","))[0]
    if len(cpos) == 0:
        # no commas anywhere: ALS -> all nonempty lines are errors; SVM ->
        # every nonempty line is (line, "")
        if mode == CHUNK_ALS:
            errs = int(nonempty.sum())
            return ([], [], errs, None) if with_hashes else ([], [], errs)
        text = data.decode("utf-8")
        keys = [ln for ln in text.splitlines() if ln]
        values = [""] * len(keys)
        if with_hashes:
            hashes = _fnv1a_ranges(buf, starts[nonempty], ends[nonempty])
            return keys, values, 0, hashes
        return keys, values, 0
    j1 = np.searchsorted(cpos, starts)
    safe1 = np.minimum(j1, len(cpos) - 1)
    c1 = cpos[safe1]
    has1 = (j1 < len(cpos)) & (c1 < ends)
    out = buf.copy()
    errors = 0
    loners = None
    if mode == CHUNK_ALS:
        j2 = j1 + 1
        safe2 = np.minimum(j2, len(cpos) - 1)
        c2 = cpos[safe2]
        has2 = has1 & (j2 < len(cpos)) & (c2 < ends)
        keep_line = nonempty & has2
        errors = int((nonempty & ~has2).sum())
        out[c1[keep_line]] = ord("-")   # "id,T" -> "id-T"
        out[c2[keep_line]] = ord("\n")  # key/value separator
        key_ends = c2  # key is "id-T": start of line .. second comma
    else:
        keep_line = nonempty  # str.partition never fails a row
        out[c1[nonempty & has1]] = ord("\n")
        # comma-less SVM rows yield (row, "") — they get an extra "\n"
        # spliced in after the mask pass so the key/value alternation
        # holds WITHOUT reordering (last-writer-wins depends on order)
        loners = np.nonzero(nonempty & ~has1)[0]
        key_ends = np.where(has1, c1, ends)
    no_loners = loners is None or len(loners) == 0
    if bool(keep_line.all()) and not bool(cr.any()):
        # clean chunk (the overwhelmingly common case): every byte is
        # kept, so skip the O(bytes) mask build and boolean gather
        kept_arr = out
        if not no_loners:
            kept_arr = np.insert(
                kept_arr, nl[loners] + 1, np.uint8(ord("\n"))
            )
    else:
        # drop malformed/empty lines (and CR bytes) in one mask pass
        line_lens = nl - starts + 1
        mask = np.repeat(keep_line, line_lens)
        mask[ends[cr]] = False
        kept_arr = out[mask]
        if not no_loners:
            # position just past each loner's newline in the kept stream
            cum = np.cumsum(mask)
            kept_arr = np.insert(
                kept_arr, cum[nl[loners]], np.uint8(ord("\n"))
            )
    # decode + split ONCE: parts alternate key, value, key, value, ...
    kept = kept_arr.tobytes()
    if kept:
        parts = kept.decode("utf-8").split("\n")
        parts.pop()  # buffer ends with "\n" -> one trailing empty
        keys, values = parts[0::2], parts[1::2]
    else:
        keys, values = [], []
    if not with_hashes:
        return keys, values, errors
    # per-key shard hashes straight from the (rewritten) chunk bytes, in
    # kept-line order — the key bytes ARE each key string's utf-8 bytes
    hashes = _fnv1a_ranges(out, starts[keep_line], key_ends[keep_line])
    return keys, values, errors, hashes


# ---------------------------------------------------------------------------
# latency CSVs (load-harness output contracts)
# ---------------------------------------------------------------------------

def format_als_latency_row(user: int, item: int, prediction: float, ms: float) -> str:
    """``uId,iId,prediction,ms`` (ALSPredictRandom.java:94)."""
    return f"{user},{item},{_fmt(prediction)},{_fmt_ms(ms)}"


def format_svm_latency_row(query_id: int, n_features: int, prediction: float,
                           ms: float) -> str:
    """``qId,nFeatures,prediction,ms`` (SVMPredictRandom.java:91)."""
    return f"{query_id},{n_features},{_fmt(prediction)},{_fmt_ms(ms)}"


# ---------------------------------------------------------------------------

def _split_semis(payload: str) -> List[str]:
    """Split on ';' with Java String.split semantics: trailing empty tokens
    are dropped, but interior empties ('1.0;;2.0') are kept so the float
    parse raises instead of silently shortening the vector."""
    toks = payload.split(";")
    while toks and toks[-1] == "":
        toks.pop()
    return toks


def _fmt(v: float) -> str:
    """Float -> shortest round-trip decimal (close analog of Java
    Double.toString for the value ranges these models produce)."""
    return repr(float(v))


def _fmt_ms(ms: float) -> str:
    # the reference logs integral milliseconds (System.currentTimeMillis diff)
    return str(int(round(ms)))
