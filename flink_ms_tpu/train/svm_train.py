"""SVM training CLI — TPU-native counterpart of ``SVMImpl``
(``flink-svm/src/main/scala/de/tub/it4bi/SVMImpl.scala``).

Reference flag surface preserved (SURVEY.md Appendix A), including the
``--iteration`` singular-form quirk (SVMImpl.scala:26 — Appendix C #1;
``--iterations`` is also accepted here as an alias): ``--training`` (req),
``--blocks`` (10), ``--iteration`` (10), ``--partition`` bool, ``--range``
(1000), ``--output``.  Output rows are 1-based ``featureIndex,weight`` or
range-partitioned ``bucket,idx:w;...`` (SVMImpl.scala:33-46).

TPU-native extras surface FlinkML's hidden CoCoA knobs [dep]:
``--localIterations`` (default: one full local pass per round),
``--regularization`` (1.0), ``--stepsize`` (1.0), ``--seed``, ``--devices``,
``--profileDir`` (XLA profiler trace of the fit).
"""

from __future__ import annotations

import sys
import time

from ..core import formats as F
from ..core.params import Params
from ..ops.svm import SVMConfig, SVMModel, prepare_svm_blocked, svm_fit
from ..parallel.distributed import is_primary, maybe_init_distributed
from ..parallel.mesh import honor_platform_env, mesh_for_blocks
from ..utils import profiling


def run(params: Params) -> SVMModel:
    training_path = params.get_required("training")
    data = F.read_libsvm(training_path)

    honor_platform_env()
    maybe_init_distributed(params)
    blocks = params.get_int("blocks", 10)
    # blocks = K logical SDCA chains; the mesh spans min(K, devices) (all
    # devices in multi-process runs), and the kernel stacks ceil(K/D)
    # chains per device when K exceeds the device count
    mesh = mesh_for_blocks(blocks, params.get_int("devices"))

    iterations = params.get_int("iteration", params.get_int("iterations", 10))
    problem = prepare_svm_blocked(
        data, blocks, seed=params.get_int("seed", 0)
    )
    local_iters = params.get_int("localIterations", problem.rows_per_block)
    config = SVMConfig(
        iterations=iterations,
        local_iterations=local_iters,
        regularization=params.get_float("regularization", 1.0),
        stepsize=params.get_float("stepsize", 1.0),
        seed=params.get_int("seed", 0),
        mode=params.get("mode", "avg"),
        # CoCoA+ smoothing: unset = provably safe gamma*K; values in
        # [1, gamma*K) are the aggressive sparse-data regime (ops/svm.py)
        sigma_prime=params.get_float("sigmaPrime"),
    )

    t0 = time.time()
    with profiling.trace(params.get("profileDir")):
        model = svm_fit(data, config, mesh, problem=problem)
    train_s = time.time() - t0
    print(
        f"[SVM] model-fitting: {data.n_examples} examples x "
        f"{data.n_features} features, {iterations} rounds x {local_iters} "
        f"local steps, {mesh.devices.size} device(s), {train_s:.2f}s, "
        f"hinge+reg objective="
        f"{model.hinge_loss(data, config.regularization):.6f}"
    )

    if not is_primary():  # one process materializes job output
        return model

    if params.get_bool("partition"):
        rows = F.format_svm_range_rows(model.weights, params.get_int("range", 1000))
    else:
        rows = F.format_svm_flat_rows(model.weights)

    if params.has("output"):
        F.write_lines(params.get_required("output"), rows)
    else:
        print("Printing result to stdout. Use --output to specify output path.")
        for row in rows:
            print(row)
    return model


def main(argv=None) -> None:
    run(Params.from_args(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    main()
