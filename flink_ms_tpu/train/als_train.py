"""ALS training CLI — TPU-native counterpart of ``ALSImpl``
(``flink-als/src/main/scala/de/tub/it4bi/ALSImpl.scala``).

Accepts the reference's flag inventory (SURVEY.md Appendix A) and writes the
same ``id,U|I,f1;f2;...`` model rows, so downstream tools (mean-vector job,
producer/consumer, clients) interoperate with files from either framework.

Flags beyond the reference (TPU-native surface):
  --implicit true      confidence-weighted implicit-feedback ALS (BASELINE.md)
  --alpha 40.0         implicit confidence scale
  --devices N          mesh size (defaults to all visible devices; the
                       reference's --blocks maps to Flink's internal blocking
                       and is accepted — blocking here always equals the mesh)
  --profileDir DIR     write an XLA profiler trace of the fit (TensorBoard)

``--temporaryPath`` (reference: stage loop intermediates to disk,
ALSImpl.scala:42-44) switches the training loop from one fused XLA program
to per-iteration steps with the factors materialized to disk at every
iteration boundary — and resumes from the latest snapshot on restart
(training checkpoint/resume, SURVEY.md §5).  A copy of the final factors is
also staged under that path.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from ..core import formats as F
from ..core.params import Params, field_delimiter_from
from ..ops.als import ALSConfig, ALSModel, als_fit, rmse
from ..parallel.distributed import is_primary, maybe_init_distributed
from ..parallel.mesh import honor_platform_env, mesh_for_blocks
from ..utils import profiling


def run(params: Params) -> ALSModel | None:
    if not params.has("input"):
        print("Use --input to specify file input.")
        return None

    delim = field_delimiter_from(params)
    users, items, ratings = F.read_ratings(
        params.get_required("input"),
        field_delimiter=delim,
        ignore_first_line=params.get_bool("ignoreFirstLine", True),
    )

    config = ALSConfig(
        num_factors=params.get_int("numFactors", 10),
        iterations=params.get_int("iterations", 10),
        lambda_=params.get_float("lambda", 0.9),
        seed=params.get_int("seed", 42),
        implicit=params.get_bool("implicit", False),
        alpha=params.get_float("alpha", 40.0),
    )

    honor_platform_env()
    maybe_init_distributed(params)
    # --blocks larger than the device count is legal in the reference (more
    # blocks than slots).  The blocked-ALS solve is exact per row, so any
    # logical block count partitions onto the D device blocks without
    # changing the result; multi-process runs always span every device
    mesh = mesh_for_blocks(params.get_int("blocks"), params.get_int("devices"))

    # get_required raises loudly on a present-but-valueless flag
    tmp = (
        params.get_required("temporaryPath").rstrip("/")
        if params.has("temporaryPath")
        else None
    )
    if tmp == "":  # "--temporaryPath /" (or all slashes) is not a usable dir
        raise ValueError("--temporaryPath must name a directory, got a bare '/'")
    t0 = time.time()
    step_timer = profiling.StepTimer("als-iteration") if tmp else None
    with profiling.trace(params.get("profileDir")):
        model = als_fit(
            users, items, ratings, config, mesh,
            temporary_path=tmp,
            step_timer=step_timer,
        )
    train_s = time.time() - t0
    if step_timer is not None and step_timer.durations_s:
        print(step_timer.summary())
    print(
        f"[ALS] model-training: {len(users)} ratings, "
        f"{len(model.user_ids)} users x {len(model.item_ids)} items, "
        f"k={config.num_factors}, {config.iterations} iters, "
        f"{mesh.devices.size} device(s), {train_s:.2f}s "
        f"({train_s / max(config.iterations, 1):.3f} s/iter), "
        f"train RMSE={rmse(model, users, items, ratings):.4f}"
    )

    if not is_primary():  # one process materializes job output
        return model

    if tmp:
        F.write_als_model(f"{tmp}/userFactors", model.user_ids, F.USER, model.user_factors)
        F.write_als_model(f"{tmp}/itemFactors", model.item_ids, F.ITEM, model.item_factors)

    if params.has("itemFactors") and params.has("userFactors"):
        F.write_als_model(
            params.get_required("itemFactors"), model.item_ids, F.ITEM, model.item_factors
        )
        F.write_als_model(
            params.get_required("userFactors"), model.user_ids, F.USER, model.user_factors
        )
    else:
        print(
            "Printing results to stdout. Use --itemFactors and --userFactors "
            "to specify output locations."
        )
        print("==== USER FACTORS ====")
        for id_, row in zip(model.user_ids, model.user_factors):
            print(F.format_als_row(id_, F.USER, row))
        print("==== ITEM FACTORS ====")
        for id_, row in zip(model.item_ids, model.item_factors):
            print(F.format_als_row(id_, F.ITEM, row))
    return model


def main(argv=None) -> None:
    run(Params.from_args(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    main()
