"""Tracing and per-step timing.

The reference's only measurement tooling is per-query wall-clock millis in
the load harnesses (ALSPredictRandom.java:62,93-94 — reproduced by the
clients in ``flink_ms_tpu.client``); its platform metrics live in the Flink
web UI [dep].  The TPU-native framework adds the two instruments SURVEY.md §5
calls for: XLA profiler traces (viewable in TensorBoard/Perfetto) and
per-step host-side timing with percentile summaries.
"""

from __future__ import annotations

import contextlib
import json
import math
import time
from typing import Dict, List, Optional


def hard_sync(x) -> float:
    """Force completion of the computation producing `x` and return one
    element as a Python float.

    ``jax.block_until_ready`` is NOT a reliable barrier on tunneled device
    backends (observed on the axon TPU tunnel: repeat executions return
    "ready" arrays whose computation is still in flight, collapsing timed
    regions to dispatch cost).  The only dependable barrier is a value
    fetch, so this dispatches a tiny on-device reduction of the first leaf
    and pulls the scalar to the host.  Use this — never bare
    block_until_ready — to end a timed region in benchmarks.
    """
    import jax
    import numpy as np

    leaf = jax.tree_util.tree_leaves(x)[0]
    if hasattr(leaf, "ravel"):
        leaf = leaf.ravel()[:1]  # dependent slice: forces the producer
    return float(np.asarray(leaf).ravel()[0])


@contextlib.contextmanager
def trace(trace_dir: Optional[str]):
    """JAX/XLA profiler trace of the enclosed block, written to `trace_dir`
    (no-op when None).  Captures device (TPU) and host activity."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


class StepTimer:
    """Wall-clock timer for repeated steps with percentile reporting.

    Usage::

        timer = StepTimer("als_iter")
        for _ in range(iters):
            with timer:
                step()
        print(timer.summary())
    """

    def __init__(self, name: str):
        self.name = name
        self.durations_s: List[float] = []
        self._t0: Optional[float] = None

    def __enter__(self) -> "StepTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        assert self._t0 is not None
        self.durations_s.append(time.perf_counter() - self._t0)
        self._t0 = None

    def percentile(self, q: float) -> float:
        if not self.durations_s:
            return float("nan")
        xs = sorted(self.durations_s)
        # nearest-rank: smallest value with cumulative share >= q
        idx = max(math.ceil(q / 100.0 * len(xs)) - 1, 0)
        return xs[min(idx, len(xs) - 1)]

    def merge(self, other: "StepTimer") -> "StepTimer":
        """Absorb another timer's observations (combining per-worker
        timers into one distribution — percentiles over the merged sample
        are exact, unlike averaging per-worker percentiles).  Returns
        self; ``other`` is untouched."""
        self.durations_s.extend(other.durations_s)
        return self

    def to_histogram(self):
        """This timer's observations bucketed into the serving plane's
        shared latency ladder (``obs.metrics.LATENCY_BUCKETS_S``) — the
        bridge that makes a bench percentile and a scraped serving
        percentile estimates over the IDENTICAL bucketization."""
        from ..obs.metrics import Histogram

        return Histogram(self.name).fill(self.durations_s)

    def stats(self) -> Dict[str, float]:
        n = len(self.durations_s)
        total = sum(self.durations_s)
        return {
            "name": self.name,
            "steps": n,
            "total_s": total,
            "mean_s": total / n if n else float("nan"),
            "p50_s": self.percentile(50),
            "p90_s": self.percentile(90),
            "p99_s": self.percentile(99),
            "p999_s": self.percentile(99.9),
        }

    def summary(self) -> str:
        s = self.stats()
        return (
            f"[{self.name}] {s['steps']} steps, {s['total_s']:.3f}s total, "
            f"mean {s['mean_s'] * 1e3:.2f}ms, p50 {s['p50_s'] * 1e3:.2f}ms, "
            f"p99 {s['p99_s'] * 1e3:.2f}ms"
        )

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.stats(), f)
            f.write("\n")
