"""Multi-host (DCN) bootstrap for training and serving jobs.

Reference control plane: a Flink JobManager coordinates TaskManagers over
Akka RPC, and every job/client is pointed at it by ``--jobManagerHost`` /
``--jobManagerPort`` flags (``QueryClientHelper.java:82-92``,
``SGD.java:127-138``).  The TPU-native equivalent is ``jax.distributed``:
one coordinator address, N processes each owning their local devices.
After initialization ``jax.devices()`` is the *global* device list, the
mesh spans every host, and XLA routes collectives over ICI within a slice
and DCN across slices — the kernels in ``ops/`` need no changes
(SURVEY.md §2.5).

Flags (same shape as the reference's control-plane flags):

  --coordinatorAddress host:port   coordinator (process 0) endpoint
  --numProcesses N                 total process count
  --processId I                    this process's rank in [0, N)

Environment fallbacks ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
``JAX_PROCESS_ID`` serve launchers that export rank info instead of
rewriting argv.  On managed TPU pods none of these are needed — JAX
auto-detects the topology and ``maybe_init_distributed`` is a no-op unless
flags are given.

Multi-process CPU runs (the test path, and the reference-like "cluster of
plain hosts" mode) use gloo for cross-process collectives.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from ..core.params import Params
from .mesh import honor_platform_env

_INITIALIZED = False


def _flag_or_env(params: Optional[Params], flag: str, env: str) -> Optional[str]:
    if params is not None:
        v = params.get(flag)
        if v is not None:
            return str(v)
    return os.environ.get(env)


def maybe_init_distributed(params: Optional[Params] = None) -> bool:
    """Initialize ``jax.distributed`` when multi-process flags are present.

    Returns True when this process is part of a multi-process job (whether
    initialized now or earlier), False for plain single-process runs.
    Idempotent: safe to call from every CLI entry point.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator = _flag_or_env(
        params, "coordinatorAddress", "JAX_COORDINATOR_ADDRESS"
    )
    if not coordinator:
        return False
    n = _flag_or_env(params, "numProcesses", "JAX_NUM_PROCESSES")
    pid = _flag_or_env(params, "processId", "JAX_PROCESS_ID")
    if n is None or pid is None:
        raise ValueError(
            "--coordinatorAddress requires --numProcesses and --processId "
            "(or JAX_NUM_PROCESSES / JAX_PROCESS_ID)"
        )
    honor_platform_env()
    platforms = str(getattr(jax.config, "jax_platforms", None) or "")
    if platforms.split(",")[0] == "cpu":
        # cross-process collectives on plain hosts ride gloo; TPU pods use
        # the native ICI/DCN path and must not see this knob
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(n),
        process_id=int(pid),
    )
    _INITIALIZED = True
    return True


def is_primary() -> bool:
    """True on the process that owns side effects (file writes, logs).

    Mirrors the reference's convention that exactly one driver materializes
    job output (``writeAsText`` runs once per job, not per TaskManager).
    """
    return jax.process_index() == 0


def to_host_array(arr) -> np.ndarray:
    """Device array -> host numpy, valid in single- and multi-process runs.

    In a multi-process job a block-sharded global array is not fully
    addressable from any one process, so materializing it requires a
    cross-host allgather (DCN); locally it is a plain copy.
    """
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
