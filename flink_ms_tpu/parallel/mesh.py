"""Device-mesh bootstrap and sharding helpers.

The reference distributes work over Flink TaskManager slots (`setBlocks` /
`setParallelism`); the TPU-native equivalent is a `jax.sharding.Mesh` whose
single "blocks" axis plays the role of the reference's block/parallelism
count (SURVEY.md §2.3).  Intra-slice exchanges ride ICI via XLA collectives
(`all_gather` for factor broadcast, `psum` for CoCoA averaging); multi-host
scaling layers DCN on top through `jax.distributed` without code changes
here — the mesh simply spans more devices.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BLOCK_AXIS = "blocks"


def honor_platform_env() -> None:
    """Apply an explicitly-set ``JAX_PLATFORMS`` before backend init.

    Some deployments pre-import jax and pin ``jax_platforms`` from site
    hooks, which silently overrides the env var JAX normally honors.  A
    user who runs a CLI with ``JAX_PLATFORMS=cpu`` (local testing, CI,
    TPU tunnel down) expects it to stick, so re-apply the env value when
    its *primary* platform differs from the pinned one.  When the primary
    already matches (e.g. env ``axon`` vs pin ``axon,cpu``) the pin is
    kept: replacing it would unregister the CPU fallback that
    ``jax.devices("cpu")`` callers (benchmark baselines, host-side eval)
    rely on.  No-op once the backend is initialized.
    """
    val = os.environ.get("JAX_PLATFORMS", "")
    if not val:
        return
    cur = str(getattr(jax.config, "jax_platforms", None) or "")
    if cur.split(",")[0] == val.split(",")[0]:
        return
    try:
        jax.config.update("jax_platforms", val)
    except Exception:
        pass  # backend already live — too late to switch, keep going


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all).

    The reference's `--blocks`/`--parallelism` flags map to the mesh size;
    a block count larger than the device count is handled inside the kernels
    by stacking multiple logical blocks per device.
    """
    if devices is None:
        honor_platform_env()
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BLOCK_AXIS,))


def block_sharding(mesh: Mesh, *, rank: int = 2) -> NamedSharding:
    """Shard the leading axis over the block axis, replicate the rest."""
    return NamedSharding(mesh, P(BLOCK_AXIS, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def num_blocks(mesh: Mesh) -> int:
    return mesh.shape[BLOCK_AXIS]
