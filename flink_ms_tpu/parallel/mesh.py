"""Device-mesh bootstrap and sharding helpers.

The reference distributes work over Flink TaskManager slots (`setBlocks` /
`setParallelism`); the TPU-native equivalent is a `jax.sharding.Mesh` whose
single "blocks" axis plays the role of the reference's block/parallelism
count (SURVEY.md §2.3).  Intra-slice exchanges ride ICI via XLA collectives
(`all_gather` for factor broadcast, `psum` for CoCoA averaging); multi-host
scaling layers DCN on top through `jax.distributed` without code changes
here — the mesh simply spans more devices.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # jax 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl

try:
    import inspect as _inspect

    _SHARD_MAP_PARAMS = frozenset(
        _inspect.signature(_shard_map_impl).parameters
    )
except (TypeError, ValueError):  # signature unavailable: assume modern names
    _SHARD_MAP_PARAMS = frozenset(("check_vma",))


def shard_map(*args, **kwargs):
    """`jax.shard_map` across jax versions: jax >= 0.6 renamed the
    replication-check knob `check_rep` -> `check_vma`; kernels here use the
    modern spelling and this shim translates it for older jax."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map_impl(*args, **kwargs)

BLOCK_AXIS = "blocks"

# Platform names whose presence in JAX_PLATFORMS counts as ambient launcher
# default rather than user intent (see honor_platform_env).  Common
# accelerator names are included so a launcher exporting JAX_PLATFORMS=tpu
# is treated the same as the axon tunnel's export.  Deployment config:
# override with FLINK_MS_TPU_AMBIENT_PLATFORMS (comma-separated), read at
# call time so tests/launchers can adjust it after import.
_DEFAULT_AMBIENT = "axon,tpu,cuda,rocm"


def _ambient_accel_platforms() -> tuple:
    return tuple(
        os.environ.get(
            "FLINK_MS_TPU_AMBIENT_PLATFORMS", _DEFAULT_AMBIENT
        ).split(",")
    )


# Plugins that reach their device over a network transport (tunnel/relay)
# and can therefore hang backend init indefinitely when that transport is
# dead.  Deliberately NOT the ambient list: popping a standard local
# plugin's factory (e.g. "tpu") breaks more than init — the name backs
# jax's known-platform registry, so pallas/Mosaic lowering registration
# fails at import.  Deployment config: FLINK_MS_TPU_REMOTE_PLUGINS.
_DEFAULT_REMOTE_PLUGINS = "axon"


def _remote_plugins() -> tuple:
    return tuple(
        os.environ.get(
            "FLINK_MS_TPU_REMOTE_PLUGINS", _DEFAULT_REMOTE_PLUGINS
        ).split(",")
    )


_CACHE_DIR_ENV = "FLINK_MS_COMPILE_CACHE_DIR"
_cache_configured = False


def enable_compile_cache() -> None:
    """Point jax's persistent compilation cache at a stable host-local dir.

    The big executables (ML-20M sweep, full-scale CoCoA fit) cost tens of
    seconds each to compile through the tunneled remote-compile service,
    and heavy compile traffic is the one observed trigger for tunnel
    wedges.  A persistent cache means a benchmark re-run (in particular
    the DRIVER'S end-of-round bench.py, which runs the exact shapes this
    session already compiled) reuses executables instead of re-paying the
    compile — fewer/shorter tunnel round-trips, lower wedge exposure.

    Explicit user config wins: a pre-set JAX_COMPILATION_CACHE_DIR (or
    FLINK_MS_COMPILE_CACHE_DIR=off) leaves everything untouched."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    want = os.environ.get(_CACHE_DIR_ENV, "")
    if want.lower() in ("off", "0", "none"):
        return
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # operator already chose a cache location
    if not want and os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # host-pinned runs (tests, degraded benches) don't pay tunnel
        # compiles, and XLA:CPU's AOT cache loader logs loud machine-
        # feature-mismatch warnings for its prefer-no-scatter pseudo-
        # features — opt in explicitly via FLINK_MS_COMPILE_CACHE_DIR
        return
    path = want or os.path.expanduser("~/.cache/flink_ms_tpu/jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return
    for knob, val in (
        ("jax_compilation_cache_dir", path),
        # cache anything that took >=2s to compile regardless of size —
        # the point is skipping tunnel compile round-trips, not disk thrift
        ("jax_persistent_cache_min_compile_time_secs", 2.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass  # knob renamed/absent on this jax version: cache may be
            # partially configured, which is still strictly better than none


def honor_platform_env() -> None:
    """Apply an explicitly-set ``JAX_PLATFORMS`` before backend init.

    Some deployments pre-import jax and pin ``jax_platforms`` from site
    hooks, which silently overrides the env var JAX normally honors.  A
    user who runs a CLI with ``JAX_PLATFORMS=cpu`` (local testing, CI,
    TPU tunnel down) expects it to stick, so re-apply it.

    An env value naming an ambient accelerator platform
    (``_ambient_accel_platforms()``) is NOT re-applied, for two reasons.
    First, the launcher exports that value into every process's
    environment, so its presence is ambient default rather than user
    intent — and it must not override an explicit in-process pin such as
    the test harness's ``jax.config.update("jax_platforms", "cpu")``.
    Second, the site pin is ``<accel>,cpu``; narrowing it to ``<accel>``
    would unregister the CPU fallback that ``jax.devices("cpu")`` callers
    (benchmark baselines, host-side eval) rely on.
    """
    enable_compile_cache()
    val = os.environ.get("JAX_PLATFORMS", "")
    if val and not any(p in val.split(",") for p in _ambient_accel_platforms()):
        try:
            jax.config.update("jax_platforms", val)
        except Exception:
            pass  # backend already live — too late to switch, keep going


def pin_host_backend() -> None:
    """Commit this process to the host CPU backend, robust to a dead
    accelerator transport.

    ``jax.devices("cpu")`` initializes EVERY registered plugin, so a
    serving worker that only wants the host backend still blocks forever
    when the accelerator tunnel is wedged.  Before any backend has
    initialized, dropping the remote-transport plugin factories
    (``_remote_plugins()``) and pinning ``jax_platforms=cpu`` makes
    host-only init unconditional; once a backend is live this is a no-op
    (the accelerator already initialized, so ``jax.devices("cpu")``
    returns promptly and placement is handled by ``device_put``)."""
    try:
        from jax._src import xla_bridge as _xb

        factories = getattr(_xb, "_backend_factories", None)
        if factories is None:
            # private attribute (known-good jax 0.4.x-0.6.x) moved in a
            # jax upgrade — see the warning below
            raise AttributeError("jax._src.xla_bridge._backend_factories")
        if not getattr(_xb, "_backends", None):
            for name in _remote_plugins():
                factories.pop(name, None)
            jax.config.update("jax_platforms", "cpu")
    except Exception as e:
        # The jax_platforms pin alone does NOT protect against a wedged
        # remote plugin: jax initializes every registered plugin, and a
        # dead transport HANGS that init rather than erroring.  Losing the
        # factory-pop path therefore degrades the wedge protection — warn
        # loudly instead of silently (ADVICE r2).
        import sys as _sys

        print(
            f"[mesh] pin_host_backend factory-pop failed on jax "
            f"{jax.__version__} ({type(e).__name__}: {e}); wedged-tunnel "
            "hang protection is INACTIVE — host-only init may block if the "
            "remote transport is down",
            file=_sys.stderr,
        )
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already live; device_put handles placement


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all)."""
    if devices is None:
        honor_platform_env()
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BLOCK_AXIS,))


def mesh_for_blocks(
    blocks: Optional[int], n_devices: Optional[int] = None
) -> Mesh:
    """Pick the mesh for a ``--blocks``/``--parallelism`` request.

    - an explicit ``--devices`` count wins;
    - multi-process runs always span every global device: a mesh capped
      below the process count could own no devices on some process, which
      would wedge that process's collectives (each process must
      participate in every mesh it is part of);
    - ``blocks <= devices``: a mesh of exactly ``blocks`` devices;
    - ``blocks > devices``: all devices — the kernels stack the extra
      logical blocks per device (the SVM kernel vmaps ceil(K/D) SDCA
      chains per device; the ALS solver is row-exact, so any logical
      block count partitions onto D device blocks without changing the
      result).
    """
    honor_platform_env()
    if n_devices is not None:
        return make_mesh(n_devices)
    if jax.process_count() > 1 or blocks is None:
        return make_mesh()
    avail = len(jax.devices())
    if blocks > avail:
        print(
            f"[mesh] --blocks {blocks} exceeds the {avail} visible "
            f"device(s); running the logical blocks on {avail} device "
            "block(s) (SVM stacks chains per device; ALS partitioning is "
            "row-exact)"
        )
    return make_mesh(min(blocks, avail))


def block_sharding(mesh: Mesh, *, rank: int = 2) -> NamedSharding:
    """Shard the leading axis over the block axis, replicate the rest."""
    return NamedSharding(mesh, P(BLOCK_AXIS, *([None] * (rank - 1))))


def row_bucket(n: int, n_shards: int, floor: int = 8) -> int:
    """Pad a row count to the next power-of-two PER-SHARD bucket.

    The same pad-to-bucket discipline as the ALS degree buckets and the
    top-k batch shapes (``warm_batch_shapes``): a catalog that grows row
    by row must not recompile its sharded programs per row, so the padded
    total is ``n_shards * 2^ceil(log2(ceil(n / n_shards)))`` — every shard
    holds the same power-of-two row count and XLA sees a handful of
    distinct shapes over the catalog's whole growth curve.  ``floor``
    bounds the per-shard size from below so tiny catalogs still give each
    shard enough rows for a local ``top_k``."""
    if n_shards < 1:
        raise ValueError("need n_shards >= 1")
    per_shard = max((max(n, 1) + n_shards - 1) // n_shards, floor)
    return n_shards * (1 << (per_shard - 1).bit_length())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def num_blocks(mesh: Mesh) -> int:
    return mesh.shape[BLOCK_AXIS]
