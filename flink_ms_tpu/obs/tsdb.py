"""Bounded in-process ring time-series store for the fleet watch loop.

The post-hoc observability stack (scrape -> ``fleet_signals`` ->
``slo.build_report``) needs the caller to hold two snapshots and only
answers questions about one window after the fact.  Continuous watching
needs *retention*: a rolling history of every scraped series so rules can
ask "what was the GET error rate over the last 60 s" or "has this gauge
gone quiet" at any moment, without a sidecar TSDB process.

``SeriesStore`` is that retention, deliberately small:

- one deque per (name, labels) series, bounded BOTH by wall-clock
  retention (``TPUMS_WATCH_RETENTION_S``, default 900 s) and point count
  (``TPUMS_WATCH_MAX_POINTS``, default 4096) — eviction happens on
  ingest, so an idle store never grows;
- scalar series hold ``(ts, value)`` points (counters stay cumulative —
  reset detection lives in the query, exactly like PromQL ``increase``);
- histogram series hold cumulative snapshot entries on the shared
  ``LATENCY_BUCKETS_S`` ladder, so a trailing-window quantile is a
  bucket-wise delta of the newest and oldest in-window samples — the
  same statistic ``metrics.bucketed_quantiles`` computes for the bench;
- queries: ``latest`` / ``points`` / ``staleness_s`` / ``increase`` /
  ``rate`` (counter-reset aware) / ``derivative`` (gauge slope) /
  ``quantile`` (windowed histogram interpolation);
- optional JSONL spill (``spill_path``) appends one compact line per
  ingest for post-mortem correlation with the ``TPUMS_TRACE`` event log.

``ingest_fleet`` adapts a ``scrape.scrape_fleet()`` result: the fleet
merge's counters/gauges/histograms plus derived watch series
(``tpums_watch_replicas_total`` / ``_replicas_ready`` /
``_unreachable_replicas`` / ``_scrape_duration_seconds``) that the
default alert rules key on.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from .metrics import snapshot_quantile

__all__ = ["SeriesStore", "series_key", "DEFAULT_RETENTION_S",
           "DEFAULT_MAX_POINTS"]

DEFAULT_RETENTION_S = 900.0
DEFAULT_MAX_POINTS = 4096


def _env_float(name: str, default: float, lo: float) -> float:
    try:
        return max(float(os.environ.get(name, default)), lo)
    except ValueError:
        return default


def series_key(name: str, labels: Optional[dict] = None
               ) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Canonical series identity: name + sorted label pairs (stringified,
    matching the snapshot JSON round-trip)."""
    items = tuple(sorted((str(k), str(v))
                         for k, v in (labels or {}).items()))
    return (name, items)


class SeriesStore:
    """Ring-buffered multi-series store.  Thread-safe: the watch loop
    ingests from its own thread while rules/tests query concurrently."""

    def __init__(self, retention_s: Optional[float] = None,
                 max_points: Optional[int] = None,
                 spill_path: Optional[str] = None):
        self.retention_s = (
            _env_float("TPUMS_WATCH_RETENTION_S", DEFAULT_RETENTION_S, 1.0)
            if retention_s is None else max(float(retention_s), 1.0))
        self.max_points = int(
            _env_float("TPUMS_WATCH_MAX_POINTS", DEFAULT_MAX_POINTS, 2)
            if max_points is None else max(int(max_points), 2))
        self.spill_path = spill_path
        self._lock = threading.Lock()
        self._scalar: Dict[tuple, Deque[Tuple[float, float]]] = {}
        self._hist: Dict[tuple, Deque[Tuple[float, dict]]] = {}
        self._ingests = 0

    # -- ingest -----------------------------------------------------------

    def _evict(self, dq: Deque, now: float) -> None:
        cutoff = now - self.retention_s
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def observe(self, name: str, value: float, ts: Optional[float] = None,
                **labels) -> None:
        """Append one scalar point (counter level or gauge value)."""
        now = time.time() if ts is None else float(ts)
        key = series_key(name, labels)
        with self._lock:
            dq = self._scalar.get(key)
            if dq is None:
                dq = self._scalar[key] = deque(maxlen=self.max_points)
            dq.append((now, float(value)))
            self._evict(dq, now)

    def observe_hist(self, name: str, hist_entry: dict,
                     ts: Optional[float] = None, **labels) -> None:
        """Append one CUMULATIVE histogram sample (a snapshot ``histograms``
        entry: ``le``/``counts``/``count``/``sum``)."""
        now = time.time() if ts is None else float(ts)
        key = series_key(name, labels)
        sample = {"le": list(hist_entry["le"]),
                  "counts": list(hist_entry["counts"]),
                  "count": int(hist_entry["count"]),
                  "sum": float(hist_entry["sum"])}
        with self._lock:
            dq = self._hist.get(key)
            if dq is None:
                dq = self._hist[key] = deque(maxlen=self.max_points)
            dq.append((now, sample))
            self._evict(dq, now)

    def ingest_snapshot(self, snap: dict, ts: Optional[float] = None,
                        extra_labels: Optional[dict] = None) -> None:
        """Ingest one metrics snapshot dict (``registry.snapshot()`` shape
        or a ``merge_snapshots`` output): counters and gauges become scalar
        points, histograms cumulative samples."""
        now = time.time() if ts is None else float(ts)
        extra = extra_labels or {}
        for c in snap.get("counters", []):
            self.observe(c["name"], c["value"], ts=now,
                         **{**c.get("labels", {}), **extra})
        for g in snap.get("gauges", []):
            self.observe(g["name"], g["value"], ts=now,
                         **{**g.get("labels", {}), **extra})
        for h in snap.get("histograms", []):
            self.observe_hist(h["name"], h, ts=now,
                              **{**h.get("labels", {}), **extra})

    def ingest_fleet(self, scrape_result: dict,
                     ts: Optional[float] = None) -> None:
        """Ingest a ``scrape_fleet()`` result: the fleet merge plus the
        derived per-tick watch series the default rules alert on."""
        now = time.time() if ts is None else float(ts)
        self.ingest_snapshot(scrape_result.get("fleet", {}), ts=now)
        replicas = scrape_result.get("replicas", [])
        ready = sum(1 for r in replicas
                    if r.get("ready") and r.get("snapshot") is not None)
        self.observe("tpums_watch_replicas_total", len(replicas), ts=now)
        self.observe("tpums_watch_replicas_ready", ready, ts=now)
        self.observe("tpums_watch_unreachable_replicas",
                     scrape_result.get("unreachable", 0), ts=now)
        if scrape_result.get("scrape_duration_s") is not None:
            self.observe("tpums_watch_scrape_duration_seconds",
                         scrape_result["scrape_duration_s"], ts=now)
        self._ingests += 1
        if self.spill_path:
            self._spill(now, scrape_result)

    def _spill(self, now: float, scrape_result: dict) -> None:
        line = {"ts": now, "kind": "watch_ingest",
                "replicas": len(scrape_result.get("replicas", [])),
                "unreachable": scrape_result.get("unreachable", 0),
                "scrape_duration_s": scrape_result.get("scrape_duration_s"),
                "gauges": {
                    g["name"]: g["value"]
                    for g in scrape_result.get("fleet", {}).get("gauges", [])
                },
                "counters": {
                    c["name"]: c["value"]
                    for c in scrape_result.get("fleet", {}).get(
                        "counters", [])
                }}
        try:
            with open(self.spill_path, "a") as f:
                f.write(json.dumps(line, separators=(",", ":"),
                                   default=str) + "\n")
        except OSError:
            pass

    # -- scalar queries ---------------------------------------------------
    #
    # Label semantics follow PromQL selectors: the given labels are a
    # SUBSET match, so a query for ``tpums_server_requests_total`` with no
    # labels aggregates across every verb the scrape saw.  No exact-key
    # short-circuit: an unlabeled series coexisting with labeled series of
    # the same name must still aggregate with them, not shadow them.

    def _matching(self, table: Dict[tuple, Deque], name: str,
                  labels: dict) -> List[Deque]:
        want = dict(series_key(name, labels)[1])
        with self._lock:
            out = []
            for (n, items), dq in table.items():
                if n != name:
                    continue
                have = dict(items)
                if all(have.get(k) == v for k, v in want.items()):
                    out.append(dq)
            return out

    def _points(self, name: str, labels: dict) -> List[Tuple[float, float]]:
        with self._lock:
            dq = self._scalar.get(series_key(name, labels))
            return list(dq) if dq else []

    def _points_multi(self, name: str, labels: dict
                      ) -> List[List[Tuple[float, float]]]:
        dqs = self._matching(self._scalar, name, labels)
        with self._lock:
            return [list(dq) for dq in dqs]

    def points(self, name: str, window_s: Optional[float] = None,
               now: Optional[float] = None, **labels
               ) -> List[Tuple[float, float]]:
        """``(ts, value)`` points, optionally only the trailing window."""
        pts = self._points(name, labels)
        if window_s is None:
            return pts
        now = time.time() if now is None else now
        cutoff = now - window_s
        return [(t, v) for t, v in pts if t >= cutoff]

    def latest(self, name: str, **labels) -> Optional[float]:
        """Latest value; with a subset match over several label sets the
        latests SUM (the fleet-merge convention for same-named gauges)."""
        series = self._points_multi(name, labels)
        vals = [pts[-1][1] for pts in series if pts]
        return sum(vals) if vals else None

    def staleness_s(self, name: str, now: Optional[float] = None,
                    **labels) -> Optional[float]:
        """Seconds since ANY matching series last received a point; None
        when never seen (absence rules treat that separately)."""
        series = self._points_multi(name, labels)
        last = max((pts[-1][0] for pts in series if pts), default=None)
        if last is None:
            return None
        now = time.time() if now is None else now
        return max(now - last, 0.0)

    @staticmethod
    def _increase_one(pts: List[Tuple[float, float]], cutoff: float
                      ) -> float:
        # anchor: latest point at-or-before the cutoff, then in-window
        anchor = None
        series: List[Tuple[float, float]] = []
        for t, v in pts:
            if t < cutoff:
                anchor = (t, v)
            else:
                series.append((t, v))
        if anchor is not None:
            series.insert(0, anchor)
        if len(series) < 2:
            return 0.0
        total = 0.0
        prev = series[0][1]
        for _, cur in series[1:]:
            total += cur if cur < prev else cur - prev
            prev = cur
        return total

    def increase(self, name: str, window_s: float,
                 now: Optional[float] = None, **labels) -> float:
        """Counter increase over the trailing window, reset-aware: a sample
        below its predecessor means the process restarted, so the sample's
        own level is the post-reset contribution (PromQL semantics).  The
        last pre-window point anchors the window so slow scrape cadences
        don't under-count.  Subset label matches sum their increases."""
        now = time.time() if now is None else now
        cutoff = now - window_s
        return sum(self._increase_one(pts, cutoff)
                   for pts in self._points_multi(name, labels))

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None, **labels) -> float:
        """Per-second counter rate over the trailing window."""
        return self.increase(name, window_s, now=now, **labels) \
            / max(window_s, 1e-9)

    def derivative(self, name: str, window_s: float,
                   now: Optional[float] = None, **labels
                   ) -> Optional[float]:
        """Gauge slope over the trailing window: (last-first)/dt.  None
        with fewer than two in-window points."""
        pts = self.points(name, window_s=window_s, now=now, **labels)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def window_max(self, name: str, window_s: float,
                   now: Optional[float] = None, **labels
                   ) -> Optional[float]:
        """Max value inside the trailing window; subset matches take the
        max of per-series window maxima."""
        now = time.time() if now is None else now
        cutoff = now - window_s
        best: Optional[float] = None
        for pts in self._points_multi(name, labels):
            for t, v in pts:
                if t >= cutoff and (best is None or v > best):
                    best = v
        return best

    # -- histogram queries ------------------------------------------------

    @staticmethod
    def _window_delta_one(samples: List[Tuple[float, dict]],
                          cutoff: float) -> Optional[dict]:
        anchor = None
        inwin = []
        for t, h in samples:
            if t < cutoff:
                anchor = h
            else:
                inwin.append(h)
        if not inwin:
            return None
        newest = inwin[-1]
        base = anchor if anchor is not None else (
            inwin[0] if len(inwin) > 1 else None)
        if base is None or base["le"] != newest["le"]:
            base = {"le": newest["le"],
                    "counts": [0] * len(newest["counts"]),
                    "count": 0, "sum": 0.0}
        counts = [a - b for a, b in zip(newest["counts"], base["counts"])]
        if any(c < 0 for c in counts):  # exporter reset mid-window
            counts = list(newest["counts"])
            base = {"counts": [0] * len(counts), "count": 0, "sum": 0.0}
        return {"le": list(newest["le"]), "counts": counts,
                "count": newest["count"] - base["count"],
                "sum": newest["sum"] - base["sum"]}

    def window_hist(self, name: str, window_s: float,
                    now: Optional[float] = None, **labels
                    ) -> Optional[dict]:
        """Delta histogram over the trailing window: per matching series,
        newest in-window cumulative sample minus the window's anchor
        sample (last at-or-before the cutoff, else the oldest in-window);
        subset matches then add bucket-wise (same ladder required — the
        scrape already enforces it fleet-wide).  Any bucket that DECREASED
        means the exporter restarted mid-window — that series' newest
        cumulative sample alone is then the best available estimate."""
        now = time.time() if now is None else now
        dqs = self._matching(self._hist, name, labels)
        with self._lock:
            all_samples = [list(dq) for dq in dqs]
        cutoff = now - window_s
        merged: Optional[dict] = None
        for samples in all_samples:
            d = self._window_delta_one(samples, cutoff)
            if d is None:
                continue
            if merged is None:
                merged = {"name": name, **d}
            elif merged["le"] == d["le"]:
                merged["counts"] = [a + b for a, b in
                                    zip(merged["counts"], d["counts"])]
                merged["count"] += d["count"]
                merged["sum"] += d["sum"]
        return merged

    def quantile(self, name: str, q: float, window_s: float,
                 now: Optional[float] = None, **labels) -> Optional[float]:
        """Interpolated quantile of the trailing window's delta histogram
        (the same bucket-interpolation statistic as the bench/scrape
        path); None with no in-window observations."""
        h = self.window_hist(name, window_s, now=now, **labels)
        if h is None or h["count"] <= 0:
            return None
        return snapshot_quantile(h, q)

    # -- introspection ----------------------------------------------------

    def series(self) -> List[tuple]:
        with self._lock:
            return sorted(list(self._scalar) + list(self._hist))

    def stats(self) -> dict:
        with self._lock:
            return {
                "scalar_series": len(self._scalar),
                "hist_series": len(self._hist),
                "points": sum(len(d) for d in self._scalar.values())
                + sum(len(d) for d in self._hist.values()),
                "ingests": self._ingests,
                "retention_s": self.retention_s,
                "max_points": self.max_points,
            }
