"""Declarative alert rules over the watch store (``obs/tsdb.py``).

The SRE-style alerting discipline, sized down to one process:

- **threshold** rules compare a query over a series (``latest`` / ``rate``
  / ``derivative`` / ``quantile`` / ``drop`` — window-max minus latest,
  the shape of "a replica vanished") against a bound;
- **absence** rules fire when a series goes quiet for longer than the
  bound — covers both a stalled exporter and a scrape loop that died;
- **burn_rate** rules implement the multi-window error-budget pattern: a
  FAST window (pages quickly on a cliff) and a SLOW window (filters
  blips) must BOTH burn the budget faster than ``burn_multiple`` before
  the rule trips, which is what makes page-severity alerts actionable
  instead of noisy;
- ``for_s`` hold-down: the condition must hold continuously before the
  alert transitions pending -> firing (Prometheus ``for:``);
- flap suppression: ``flap_max`` fire/resolve cycles inside
  ``flap_window_s`` latch the alert firing with ``suppressed=True`` so a
  boundary-riding signal produces one page, not a pager storm;
- every transition is emitted as an ``alert_firing`` /
  ``alert_resolved`` tracing event, so the incident timeline and the
  Dapper-style request log land in the same ring/JSONL stream.

Attribution reuses the SLO report's machinery: ``attribute_alerts`` maps
each firing to the nearest disruptive event (kill, cutover, rollout,
autoscale decision) within the attribution window —
``unattributed == 0`` is the chaos gate, meaning nothing paged that the
run cannot explain.

Rules files are JSON (``load_rules``): ``{"rules": [{...}, ...]}`` or a
bare list, field names matching ``Rule``'s constructor.  ``default_rules``
ships the fleet baseline: replica drop + unreachable pages, scrape
staleness, server error burn rate, and the model-drift threshold over the
canary's ``tpums_model_live_mse``.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence

from . import tracing
from .slo import DEFAULT_ATTRIBUTION_WINDOW_S, _attribute_time
from .tsdb import SeriesStore

__all__ = ["Rule", "RulesEngine", "load_rules", "default_rules",
           "attribute_alerts", "SEVERITY_LEVEL", "severity_name"]

SEVERITY_LEVEL = {"info": 1, "warn": 2, "page": 3}


def severity_name(level: float) -> Optional[str]:
    for name, lv in SEVERITY_LEVEL.items():
        if lv == int(level):
            return name
    return None


_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


@dataclass
class Rule:
    """One declarative alert rule.  ``kind`` selects the evaluator:

    - ``threshold``: measure ``series`` via ``mode`` (``latest`` | ``rate``
      | ``derivative`` | ``quantile`` over ``window_s``, quantile ``q``;
      ``drop`` = window-max minus latest) and compare ``op value``;
    - ``absence``: fire when ``series`` has been silent > ``value``
      seconds (a never-seen series counts its silence from engine start);
    - ``burn_rate``: error-budget burn from ``errors_series`` /
      ``requests_series`` increases — fires only when BOTH
      ``fast_window_s`` and ``slow_window_s`` burn >= ``burn_multiple``
      times the budget implied by ``availability_target``.
    """
    name: str
    kind: str = "threshold"
    series: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    op: str = ">"
    value: float = 0.0
    mode: str = "latest"
    window_s: float = 60.0
    q: float = 99.0
    for_s: float = 0.0
    severity: str = "warn"
    # burn-rate fields
    requests_series: str = ""
    errors_series: str = ""
    availability_target: float = 0.999
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_multiple: float = 14.4
    # flap suppression
    flap_max: int = 3
    flap_window_s: float = 120.0
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("threshold", "absence", "burn_rate"):
            raise ValueError(f"rule {self.name!r}: unknown kind "
                             f"{self.kind!r}")
        if self.severity not in SEVERITY_LEVEL:
            raise ValueError(f"rule {self.name!r}: unknown severity "
                             f"{self.severity!r}")
        if self.kind == "threshold" and self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op {self.op!r}")
        if self.kind == "burn_rate" and not (
                self.requests_series and self.errors_series):
            raise ValueError(f"rule {self.name!r}: burn_rate needs "
                             "requests_series and errors_series")

    # -- measurement ------------------------------------------------------

    def measure(self, store: SeriesStore, now: float,
                engine_start: float) -> Optional[dict]:
        """-> {"measured": float, "breach": bool, ...detail} or None when
        the rule has no data to judge (no data is never a breach for
        threshold/burn rules; absence is the rule FOR no data)."""
        if self.kind == "absence":
            stale = store.staleness_s(self.series, now=now, **self.labels)
            if stale is None:
                # never seen: silent since the engine started watching
                stale = max(now - engine_start, 0.0)
            return {"measured": stale, "breach": stale > self.value}
        if self.kind == "burn_rate":
            budget = max(1.0 - self.availability_target, 1e-9)
            burns = {}
            for label, win in (("fast", self.fast_window_s),
                               ("slow", self.slow_window_s)):
                req = store.increase(self.requests_series, win, now=now,
                                     **self.labels)
                err = store.increase(self.errors_series, win, now=now,
                                     **self.labels)
                if req <= 0:
                    burns[label] = 0.0
                else:
                    burns[label] = (err / req) / budget
            breach = (burns["fast"] >= self.burn_multiple
                      and burns["slow"] >= self.burn_multiple)
            return {"measured": min(burns["fast"], burns["slow"]),
                    "breach": breach, "burn_fast": burns["fast"],
                    "burn_slow": burns["slow"]}
        # threshold
        if self.mode == "latest":
            measured = store.latest(self.series, **self.labels)
        elif self.mode == "rate":
            measured = store.rate(self.series, self.window_s, now=now,
                                  **self.labels)
        elif self.mode == "derivative":
            measured = store.derivative(self.series, self.window_s,
                                        now=now, **self.labels)
        elif self.mode == "quantile":
            measured = store.quantile(self.series, self.q, self.window_s,
                                      now=now, **self.labels)
        elif self.mode == "drop":
            peak = store.window_max(self.series, self.window_s, now=now,
                                    **self.labels)
            cur = store.latest(self.series, **self.labels)
            measured = (peak - cur) if (peak is not None
                                        and cur is not None) else None
        else:
            raise ValueError(f"rule {self.name!r}: unknown mode "
                             f"{self.mode!r}")
        if measured is None:
            return None
        return {"measured": float(measured),
                "breach": _OPS[self.op](float(measured), self.value)}

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "series": self.series or None,
                "severity": self.severity, "value": self.value,
                "for_s": self.for_s}


class _AlertState:
    """Per-rule pending/firing state machine + flap history."""

    __slots__ = ("state", "pending_since", "firing_since", "measured",
                 "detail", "cycles", "suppressed")

    def __init__(self):
        self.state = "ok"            # ok | pending | firing
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.measured: Optional[float] = None
        self.detail: dict = {}
        self.cycles: Deque[float] = deque(maxlen=64)  # resolve timestamps
        self.suppressed = False


class RulesEngine:
    """Evaluate a rule set against a ``SeriesStore`` on every watch tick.

    ``evaluate`` returns the tick's TRANSITIONS (fired/resolved dicts) and
    appends them to ``history`` — the incident timeline.  ``active``/
    ``summary`` expose current state for gauges, HEALTH hints and the
    registry alert record."""

    def __init__(self, rules: Sequence[Rule],
                 now: Optional[float] = None):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError("duplicate rule names")
        self.rules = list(rules)
        self.started_at = time.time() if now is None else now
        self.history: List[dict] = []
        self._state: Dict[str, _AlertState] = {
            r.name: _AlertState() for r in self.rules}

    # -- evaluation -------------------------------------------------------

    def evaluate(self, store: SeriesStore,
                 now: Optional[float] = None) -> List[dict]:
        now = time.time() if now is None else now
        transitions: List[dict] = []
        for rule in self.rules:
            st = self._state[rule.name]
            res = rule.measure(store, now, self.started_at)
            breach = bool(res and res["breach"])
            if res is not None:
                st.measured = res["measured"]
                st.detail = {k: v for k, v in res.items()
                             if k not in ("breach",)}
            if breach:
                if st.state == "ok":
                    st.state = "pending"
                    st.pending_since = now
                if st.state == "pending" and \
                        now - st.pending_since >= rule.for_s:
                    st.state = "firing"
                    st.firing_since = now
                    transitions.append(self._transition(
                        "alert_firing", rule, st, now))
            else:
                if st.state == "firing":
                    # a flap cycle is recorded only while un-latched:
                    # once suppressed, every clear tick would otherwise
                    # refill the window and the latch could never drain
                    if not st.suppressed:
                        st.cycles.append(now)
                    if self._flapping(rule, st, now):
                        # latch: stay firing, mark suppressed once
                        if not st.suppressed:
                            st.suppressed = True
                            transitions.append(self._transition(
                                "alert_suppressed", rule, st, now))
                    else:
                        # resolve — including un-latching a suppressed
                        # flap once its window has gone quiet
                        st.state = "ok"
                        st.firing_since = None
                        st.pending_since = None
                        st.suppressed = False
                        transitions.append(self._transition(
                            "alert_resolved", rule, st, now))
                elif st.state == "pending":
                    st.state = "ok"
                    st.pending_since = None
        self.history.extend(transitions)
        for tr in transitions:
            tracing.event(tr["kind"], rule=tr["rule"],
                          severity=tr["severity"],
                          measured=tr.get("measured"))
        return transitions

    def _flapping(self, rule: Rule, st: _AlertState, now: float) -> bool:
        recent = [t for t in st.cycles if now - t <= rule.flap_window_s]
        return len(recent) >= rule.flap_max

    def _transition(self, kind: str, rule: Rule, st: _AlertState,
                    now: float) -> dict:
        tr = {"ts": now, "kind": kind, "rule": rule.name,
              "severity": rule.severity, "measured": st.measured,
              "value": rule.value if rule.kind != "burn_rate"
              else rule.burn_multiple}
        if st.suppressed:
            tr["suppressed"] = True
        for k, v in st.detail.items():
            if k != "measured":
                tr[k] = v
        return tr

    # -- state ------------------------------------------------------------

    def active(self) -> List[dict]:
        """Currently-firing alerts (suppressed flaps included — they are
        still real conditions, just deduplicated)."""
        out = []
        for rule in self.rules:
            st = self._state[rule.name]
            if st.state == "firing":
                out.append({"rule": rule.name, "severity": rule.severity,
                            "since": st.firing_since,
                            "measured": st.measured,
                            "suppressed": st.suppressed,
                            "description": rule.description})
        return out

    def summary(self) -> dict:
        """Compact state for gauges / HEALTH hints / registry records."""
        alerts = self.active()
        max_sev = max((SEVERITY_LEVEL[a["severity"]] for a in alerts),
                      default=0)
        return {"firing": len(alerts),
                "max_severity": severity_name(max_sev) if max_sev else None,
                "max_severity_level": max_sev,
                "alerts": alerts}


# ---------------------------------------------------------------------------
# attribution — the incident timeline gate
# ---------------------------------------------------------------------------

def attribute_alerts(transitions: Sequence[dict],
                     timeline: Sequence[dict],
                     window_s: float = DEFAULT_ATTRIBUTION_WINDOW_S
                     ) -> dict:
    """Attribute each ``alert_firing`` transition to the nearest disruptive
    timeline event (same machinery and window as the SLO report's breach
    attribution).  ``unattributed`` counts firings with NO explaining
    event — the chaos gate requires it to be zero for page severity."""
    attributed: List[dict] = []
    unattributed = 0
    unattributed_page = 0
    for tr in transitions:
        if tr.get("kind") != "alert_firing":
            continue
        cause = _attribute_time(tr["ts"], timeline, (), window_s)
        entry = dict(tr)
        entry["attributed_to"] = cause
        if cause is None:
            unattributed += 1
            if tr.get("severity") == "page":
                unattributed_page += 1
        attributed.append(entry)
    return {"alerts": attributed, "unattributed": unattributed,
            "unattributed_page": unattributed_page,
            "window_s": window_s}


# ---------------------------------------------------------------------------
# rule sets
# ---------------------------------------------------------------------------

def load_rules(path: str) -> List[Rule]:
    """Parse a JSON rules file: ``{"rules": [{...}]}`` or a bare list of
    rule objects whose keys match ``Rule``'s fields."""
    with open(path) as f:
        doc = json.load(f)
    items = doc.get("rules", []) if isinstance(doc, dict) else doc
    if not isinstance(items, list):
        raise ValueError(f"{path}: expected a list or {{'rules': [...]}}")
    return [Rule(**item) for item in items]


def default_rules() -> List[Rule]:
    """The fleet baseline.  Replica loss pages on the DROP shape (a
    SIGKILL'd same-host replica is pid-dead and reaped from the registry
    listing almost immediately, so 'unreachable' alone can miss it — the
    replica COUNT falling below its window peak is the robust signal)."""
    return [
        Rule(name="replica_drop", kind="threshold",
             series="tpums_watch_replicas_total", mode="drop",
             window_s=60.0, op=">=", value=1.0, for_s=0.0,
             severity="page",
             description="live replica count fell below its 60s peak"),
        Rule(name="replicas_unreachable", kind="threshold",
             series="tpums_watch_unreachable_replicas", mode="latest",
             op=">=", value=1.0, for_s=0.0, severity="page",
             description="registered replica not answering METRICS"),
        Rule(name="scrape_stalled", kind="absence",
             series="tpums_watch_replicas_total", value=15.0,
             severity="warn",
             description="watch scrape loop has gone quiet"),
        Rule(name="server_error_burn", kind="burn_rate",
             requests_series="tpums_server_requests_total",
             errors_series="tpums_server_errors_total",
             availability_target=0.999, fast_window_s=60.0,
             slow_window_s=300.0, burn_multiple=14.4, for_s=0.0,
             severity="page",
             description="error budget burning at page rate in both "
                         "fast and slow windows"),
        Rule(name="model_drift", kind="threshold",
             series="tpums_model_live_mse", mode="latest",
             op=">", value=2.0, for_s=0.0, severity="warn",
             description="live held-out MSE above drift threshold"),
        # newer-plane baselines (round 19): these signals have existed in
        # fleet_signals since rounds 15-17 but nothing paged on them —
        # sustained CAS retries mean update workers are losing races to
        # the ingest writer (LWW re-put churn), seqlock read retries mean
        # hot-row write contention on the lock-free read path, and
        # follower lag is the staleness bound georepl promises readers
        Rule(name="arena_cas_retry_storm", kind="threshold",
             series="tpums_arena_cas_retry_total", mode="rate",
             window_s=60.0, op=">", value=100.0, for_s=30.0,
             severity="warn",
             description="arena CAS retries sustained above 100/s — "
                         "update plane losing races to the ingest writer"),
        Rule(name="arena_read_retry_storm", kind="threshold",
             series="tpums_arena_read_retries_total", mode="rate",
             window_s=60.0, op=">", value=1000.0, for_s=30.0,
             severity="warn",
             description="seqlock read retries sustained above 1000/s — "
                         "hot-row write contention on the lock-free path"),
        Rule(name="georepl_follower_lag", kind="threshold",
             series="tpums_georepl_lag_seconds", mode="latest",
             op=">", value=30.0, for_s=30.0, severity="page",
             description="follower region trailing its leader by >30s"),
        # continuous-profiling plane (round 19): a CPU regression pages,
        # and the page carries profdiff's top-delta frames (the watcher
        # diffs its previous profiler snapshot against the current one),
        # closing the chain alert -> stage -> frames
        Rule(name="process_cpu_regression", kind="threshold",
             series="tpums_process_cpu_seconds_total", mode="rate",
             window_s=60.0, op=">", value=0.9, for_s=30.0,
             severity="warn",
             description="process burning >0.9 CPU cores sustained — "
                         "see attached profile_top_frames"),
    ]
