"""Process-wide metrics registry — the serving plane's measurement spine.

The reference's only measurement surface is per-query wall-clock millis in
its load clients plus the Flink web UI (``utils/profiling.py`` docstring,
SURVEY §5).  This module is the Prometheus-style pull half of the answer:
every subsystem (lookup server, top-k microbatcher, ingest loop, replica
supervisor) registers monotonic **counters**, **gauges**, and fixed-bucket
log-spaced latency **histograms** in one process-wide registry, and the
whole registry is exposed as

- a single-line JSON snapshot (the ``METRICS`` wire verb, ``server.py``),
- a Prometheus text exposition (``render_prometheus``), and
- a fleet aggregate (``merge_snapshots`` — sum counters/gauges, add
  histograms bucket-wise; ``obs/scrape.py`` walks the job registry and
  merges every live replica).

Design constraints, in order:

- **No per-observation allocation.**  ``Histogram.observe`` is a bisect
  into a precomputed boundary list plus two integer adds — no numpy array,
  no dict, no string is built on the hot path.
- **Safe under the server's thread-per-connection model.**  CPython's
  ``+=`` on an attribute is a read-modify-write that CAN lose updates
  across threads, so every instrument takes one (cheap, uncontended) lock
  per observation; the concurrency test pins exact totals.
- **Free when off.**  ``TPUMS_METRICS=0`` turns every observation into a
  single attribute check and an early return, so the A/B overhead story
  (README "Observability") is measurable in one process.

Instruments are identified by ``(name, labels)``; re-requesting the same
pair returns the SAME instrument (get-or-create), so call sites cache the
instrument once and pay only the observation afterwards.
"""

from __future__ import annotations

import json
import os
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# enable switch
# ---------------------------------------------------------------------------

_ENABLED = os.environ.get("TPUMS_METRICS", "1") != "0"


def metrics_enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Flip metric collection live (bench A/B, tests) -> previous value.
    Instruments keep existing either way; observations become no-ops."""
    global _ENABLED
    prev, _ENABLED = _ENABLED, bool(on)
    return prev


# Exemplars: when on, each histogram bucket remembers the last
# ``(tid, value, ts)`` observed under an active trace, so any p99 number
# resolves to a concrete trace id.  Off by default — the hot path then
# pays exactly one module-global check per observation.
_EXEMPLARS = os.environ.get("TPUMS_EXEMPLARS", "0") != "0"

# The trace id rides INTO ``observe(v, tid=...)`` explicitly: the call
# sites that can link an observation to a trace (serve/server.py request
# epilogue) already hold the wire tid, and an untraced observation then
# pays literally nothing for the feature — no thread-local read, no
# provider call.  (A provider indirection was tried first and its ~0.1us
# per-observe read alone threatened the 3% hot-path bar that
# scripts/obs_overhead_ab.py enforces.)


def exemplars_enabled() -> bool:
    return _EXEMPLARS


def set_exemplars(on: bool) -> bool:
    """Flip exemplar retention live (bench A/B, tests) -> previous value."""
    global _EXEMPLARS
    prev, _EXEMPLARS = _EXEMPLARS, bool(on)
    return prev


# ---------------------------------------------------------------------------
# shared bucket ladder
# ---------------------------------------------------------------------------

def log_buckets(lo: float, hi: float, per_decade: int = 16) -> Tuple[float, ...]:
    """Log-spaced upper bounds from ``lo`` to >= ``hi`` (``per_decade``
    buckets per factor of 10).  Bounds are generated once and shared; the
    per-observation cost is a bisect, independent of bucket count."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError("need 0 < lo < hi and per_decade >= 1")
    ratio = 10.0 ** (1.0 / per_decade)
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * ratio)
    return tuple(out)


# One ladder for every latency series in the repo — serving verbs, queue
# waits, ingest applies, AND the bench harness percentiles
# (bench_sections._pcts / StepTimer route through these same bounds, so a
# bench p50 and a scraped serving p50 are estimates over the identical
# bucketization).  1 µs .. 100 s at 16 buckets/decade: interpolated
# quantiles land within ~7% of the exact rank statistic.
LATENCY_BUCKETS_S: Tuple[float, ...] = log_buckets(1e-6, 100.0, 16)

# Batch-size style ladder (1 .. 64k, 8/decade is plenty for integers).
SIZE_BUCKETS: Tuple[float, ...] = log_buckets(1.0, 65536.0, 8)

# Metric-name hygiene contract, enforced by a tier-1 lint
# (tests/test_metric_hygiene.py) that walks the live registry after an
# end-to-end smoke: every series name matches NAME_PATTERN, counters end
# in ``_total``, and label KEYS come from this closed vocabulary.  Label
# keys are schema — dashboards, recording rules, and the fleet merge all
# join on them — so adding one is a deliberate act here, not a drive-by
# in an instrument call.  (Label VALUES stay free-form.)
NAME_PATTERN = r"^tpums_[a-z0-9_]+$"
LABEL_VOCABULARY = frozenset({
    "verb",     # wire verb (GET/MGET/TOPK/...)
    "state",    # model state / table name
    "tenant",   # admission-control tenant id
    "kind",     # generic discriminator (event kind, rollout kind, ...)
    "result",   # outcome discriminator (won/lost/fired/...)
    "pid",      # per-process series that must NOT sum across a fleet
    "topic",    # journal/georepl topic
    "region",   # geo region id
})


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------

class Counter:
    """Monotonic counter.  ``inc`` never goes backwards; negative
    increments are rejected (that's what gauges are for)."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if not _ENABLED:
            return
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (backlog bytes, rows/s)."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with log-spaced upper bounds.

    ``observe(v)`` counts ``v`` into the first bucket whose upper bound is
    >= v (Prometheus ``le`` semantics; values above the last bound land in
    the implicit +Inf bucket) — one bisect into a precomputed tuple plus
    two adds, zero allocation.  ``quantile(q)`` returns the interpolated
    value the way ``histogram_quantile`` does: uniform within the winning
    bucket, lower edge 0 for the first.  ``merge`` adds two histograms
    bucket-wise (associative and commutative — the fleet-scrape identity
    the tests pin)."""

    __slots__ = ("name", "labels", "bounds", "_lock", "_counts",
                 "_sum", "_count", "_exemplars")

    def __init__(self, name: str,
                 labels: Tuple[Tuple[str, str], ...] = (),
                 bounds: Sequence[float] = LATENCY_BUCKETS_S):
        bounds = tuple(float(b) for b in bounds)
        if not bounds or any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError("bucket bounds must be strictly increasing")
        self.name = name
        self.labels = labels
        self.bounds = bounds
        self._lock = threading.Lock()
        # one slot per bound + the +Inf overflow slot
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0
        # bucket index -> (tid, value, ts): last traced observation per
        # bucket, populated only while exemplars are on AND a trace is
        # active — bounded at one entry per bucket by construction
        self._exemplars: Dict[int, tuple] = {}

    def observe(self, v: float, tid: Optional[str] = None) -> None:
        if not _ENABLED:
            return
        i = bisect_left(self.bounds, v)
        if tid is not None and _EXEMPLARS:
            with self._lock:
                self._counts[i] += 1
                self._sum += v
                self._count += 1
                self._exemplars[i] = (tid, v, time.time())
            return
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)

    def exemplars(self) -> Dict[int, tuple]:
        """Snapshot of the per-bucket (tid, value, ts) exemplars."""
        with self._lock:
            return dict(self._exemplars)

    def fill(self, values: Sequence[float]) -> "Histogram":
        """Bulk-load observations IGNORING the enable switch — for
        offline re-bucketing of values that already exist (bench
        percentiles, StepTimer bridging), where collection cost is not
        the concern and the math must work even under TPUMS_METRICS=0."""
        with self._lock:
            for v in values:
                self._counts[bisect_left(self.bounds, v)] += 1
                self._sum += v
                self._count += 1
        return self

    def quantile(self, q: float) -> float:
        """Interpolated quantile estimate in [0, 100]; nan when empty.
        The +Inf bucket clamps to the last finite bound (Prometheus
        behavior — an estimate, loud in being one)."""
        if not (0 <= q <= 100):
            raise ValueError("q must be in [0, 100]")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return float("nan")
        rank = q / 100.0 * total
        cum = 0.0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self.bounds):  # +Inf bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - prev_cum) / c if c else 0.0
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self.bounds[-1]

    def merge(self, other: "Histogram") -> "Histogram":
        """self += other (bounds must match) -> self."""
        if other.bounds != self.bounds:
            raise ValueError(
                f"histogram bucket mismatch for {self.name!r}: "
                f"{len(self.bounds)} vs {len(other.bounds)} bounds"
            )
        with other._lock:
            o_counts = list(other._counts)
            o_sum, o_count = other._sum, other._count
        with self._lock:
            for i, c in enumerate(o_counts):
                self._counts[i] += c
            self._sum += o_sum
            self._count += o_count
        return self


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Get-or-create instrument store.  One process-wide instance
    (``get_registry``) backs every subsystem; private instances exist only
    for tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[tuple, Counter] = {}
        self._gauges: Dict[tuple, Gauge] = {}
        self._histograms: Dict[tuple, Histogram] = {}

    def counter(self, name: str, **labels: str) -> Counter:
        key = (name, _label_key(labels))
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter(name, key[1]))
        return c

    def gauge(self, name: str, **labels: str) -> Gauge:
        key = (name, _label_key(labels))
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge(name, key[1]))
        return g

    def histogram(self, name: str, bounds: Sequence[float] = LATENCY_BUCKETS_S,
                  **labels: str) -> Histogram:
        key = (name, _label_key(labels))
        h = self._histograms.get(key)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(
                    key, Histogram(name, key[1], bounds))
        return h

    def snapshot(self, meta: Optional[dict] = None) -> dict:
        """JSON-able point-in-time dump of every instrument (the METRICS
        verb's payload and the scraper's merge unit)."""
        out = {
            "ts": time.time(),
            "enabled": _ENABLED,
            "counters": [], "gauges": [], "histograms": [],
        }
        if meta:
            out["meta"] = dict(meta)
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        for c in counters:
            out["counters"].append(
                {"name": c.name, "labels": dict(c.labels), "value": c.value})
        for g in gauges:
            out["gauges"].append(
                {"name": g.name, "labels": dict(g.labels), "value": g.value})
        for h in hists:
            with h._lock:
                counts = list(h._counts)
                s, n = h._sum, h._count
                ex = {str(i): list(rec) for i, rec in h._exemplars.items()}
            entry = {
                "name": h.name, "labels": dict(h.labels),
                "le": list(h.bounds), "counts": counts,
                "sum": s, "count": n,
            }
            if ex:
                entry["exemplars"] = ex
            out["histograms"].append(entry)
        return out

    def reset(self) -> None:
        """Drop every instrument (tests; never used in serving)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


# ---------------------------------------------------------------------------
# snapshot algebra (fleet scrape, bench deltas)
# ---------------------------------------------------------------------------

def _series_key(entry: dict) -> tuple:
    return (entry["name"], tuple(sorted(entry.get("labels", {}).items())))


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Aggregate N snapshots into one: counters and gauges sum, histograms
    add bucket-wise (identical bounds required — every replica runs the
    same ladder; a mismatched series is carried under ``skipped``).  The
    operation is associative and commutative, so per-shard merges compose
    into fleet totals in any order."""
    out: dict = {"ts": time.time(), "merged_from": len(snaps),
                 "counters": [], "gauges": [], "histograms": []}
    acc_c: Dict[tuple, dict] = {}
    acc_g: Dict[tuple, dict] = {}
    acc_h: Dict[tuple, dict] = {}
    skipped: List[str] = []
    for snap in snaps:
        for e in snap.get("counters", []):
            k = _series_key(e)
            cur = acc_c.get(k)
            if cur is None:
                acc_c[k] = {"name": e["name"],
                            "labels": dict(e.get("labels", {})),
                            "value": e["value"]}
            else:
                cur["value"] += e["value"]
        for e in snap.get("gauges", []):
            k = _series_key(e)
            cur = acc_g.get(k)
            if cur is None:
                acc_g[k] = {"name": e["name"],
                            "labels": dict(e.get("labels", {})),
                            "value": e["value"]}
            else:
                cur["value"] += e["value"]
        for e in snap.get("histograms", []):
            k = _series_key(e)
            cur = acc_h.get(k)
            if cur is None:
                acc_h[k] = {"name": e["name"],
                            "labels": dict(e.get("labels", {})),
                            "le": list(e["le"]),
                            "counts": list(e["counts"]),
                            "sum": e["sum"], "count": e["count"]}
                if e.get("exemplars"):
                    acc_h[k]["exemplars"] = {
                        b: list(rec) for b, rec in e["exemplars"].items()}
            elif cur["le"] != list(e["le"]):
                skipped.append(e["name"])
            else:
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], e["counts"])]
                cur["sum"] += e["sum"]
                cur["count"] += e["count"]
                # exemplars keep the freshest per bucket across replicas
                for b, rec in (e.get("exemplars") or {}).items():
                    old = cur.get("exemplars", {}).get(b)
                    if old is None or rec[2] >= old[2]:
                        cur.setdefault("exemplars", {})[b] = list(rec)
    out["counters"] = [acc_c[k] for k in sorted(acc_c)]
    out["gauges"] = [acc_g[k] for k in sorted(acc_g)]
    out["histograms"] = [acc_h[k] for k in sorted(acc_h)]
    if skipped:
        out["skipped"] = sorted(set(skipped))
    return out


def synthesize_requests(snapshot: dict,
                        hist_name: str = "tpums_server_latency_seconds",
                        counter_name: str = "tpums_server_requests_total",
                        ) -> dict:
    """Derive the per-verb ``tpums_server_requests_total`` counter series
    from the latency histogram's count, in place -> the snapshot.

    Every request observes its latency exactly once, so the histogram
    count IS the request count; materializing the counter here (snapshot
    time, scrape rate) instead of inc'ing a second instrument on every
    request halves the hot path's locked operations.  Merge stays
    consistent: counters sum and the underlying histogram counts sum."""
    have = {_series_key(e) for e in snapshot.get("counters", [])}
    for h in snapshot.get("histograms", []):
        if h["name"] != hist_name:
            continue
        entry = {"name": counter_name,
                 "labels": dict(h.get("labels", {})),
                 "value": h["count"]}
        if _series_key(entry) not in have:
            snapshot["counters"].append(entry)
    return snapshot


def bucketed_quantiles(values: Sequence[float], qs: Sequence[float],
                       bounds: Sequence[float] = LATENCY_BUCKETS_S
                       ) -> List[float]:
    """Interpolated quantiles of ``values`` computed THROUGH the shared
    bucket ladder — the same estimate a scraped serving histogram yields
    for the same data.  The bench harness routes its percentiles through
    this so a bench p50 and a fleet-scrape p50 are the identical
    statistic, not an exact-rank number compared against a bucket
    interpolation.  Pure computation: unaffected by the enable switch."""
    h = Histogram("_bucketed", bounds=bounds).fill(values)
    return [h.quantile(q) for q in qs]


def snapshot_quantile(hist_entry: dict, q: float) -> float:
    """Interpolated quantile straight off a snapshot's histogram entry
    (the scraper aggregates dicts, not live Histogram objects)."""
    h = Histogram(hist_entry["name"], bounds=hist_entry["le"])
    h._counts = list(hist_entry["counts"])
    h._count = hist_entry["count"]
    h._sum = hist_entry["sum"]
    return h.quantile(q)


def diff_snapshots(before: dict, after: dict) -> dict:
    """Compact before/after delta for bench detail records: counters that
    moved, histogram count/sum deltas, and gauges at their AFTER value
    (gauges are levels, not flows)."""
    def index(snap, kind):
        return {_series_key(e): e for e in snap.get(kind, [])}

    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    b_c = index(before, "counters")
    for k, e in index(after, "counters").items():
        d = e["value"] - b_c.get(k, {}).get("value", 0)
        if d:
            out["counters"][_fmt_series(e)] = d
    for k, e in index(after, "gauges").items():
        if e["value"]:
            out["gauges"][_fmt_series(e)] = round(e["value"], 6)
    b_h = index(before, "histograms")
    for k, e in index(after, "histograms").items():
        prev = b_h.get(k, {"count": 0, "sum": 0.0})
        dc = e["count"] - prev["count"]
        if dc:
            out["histograms"][_fmt_series(e)] = {
                "count": dc, "sum": round(e["sum"] - prev["sum"], 6)}
    return out


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _fmt_series(entry: dict, name: Optional[str] = None,
                extra: Optional[dict] = None) -> str:
    labels = dict(entry.get("labels", {}))
    if extra:
        labels.update(extra)
    base = name or entry["name"]
    if not labels:
        return base
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
    return f"{base}{{{inner}}}"


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_float(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return repr(float(v))


def render_prometheus(snapshot: dict) -> str:
    """Snapshot -> Prometheus text exposition format 0.0.4 (counters as
    ``counter``, gauges as ``gauge``, histograms as cumulative ``_bucket``
    series plus ``_sum``/``_count``)."""
    lines: List[str] = []
    seen_type: set = set()

    def typ(name: str, kind: str) -> None:
        if name not in seen_type:
            seen_type.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for e in snapshot.get("counters", []):
        typ(e["name"], "counter")
        lines.append(f"{_fmt_series(e)} {e['value']}")
    for e in snapshot.get("gauges", []):
        typ(e["name"], "gauge")
        lines.append(f"{_fmt_series(e)} {_fmt_float(e['value'])}")
    for e in snapshot.get("histograms", []):
        name = e["name"]
        typ(name, "histogram")
        cum = 0
        for bound, c in zip(e["le"], e["counts"]):
            cum += c
            lines.append(
                f"{_fmt_series(e, name + '_bucket', {'le': _fmt_float(bound)})}"
                f" {cum}"
            )
        cum += e["counts"][len(e["le"])] if len(e["counts"]) > len(e["le"]) else 0
        lines.append(
            f"{_fmt_series(e, name + '_bucket', {'le': '+Inf'})} {cum}")
        lines.append(f"{_fmt_series(e, name + '_sum')} {_fmt_float(e['sum'])}")
        lines.append(f"{_fmt_series(e, name + '_count')} {e['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_to_json_line(snapshot: dict) -> str:
    """Single-line JSON (the METRICS verb's wire payload — the protocol is
    line-framed, so the snapshot must never contain a raw newline)."""
    return json.dumps(snapshot, separators=(",", ":"))
