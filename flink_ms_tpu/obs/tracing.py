"""Dapper-style request tracing over the tab-separated wire protocol.

A trace is a 16-hex-char id that a client stamps onto a request as a
trailing ``tid=<id>`` tab field, the server echoes back, and every hop in
between (shard fan-out threads, HA failover retries, the microbatch
dispatcher) records against as structured **events**: one JSON object per
event with ``ts``/``tid``/``kind`` plus free-form span fields (queue wait,
batch size, device seconds).  Reconstructing one slow request end to end
is then a filter of the event log by tid.

On top of the flat events sits a **span** layer: an event that also
carries ``sid`` (8-hex span id), ``psid`` (parent span id), ``t0`` (wall
start) and ``dur_s`` is a timed node in the request's causal tree.  A
thread-local span stack parents nested spans automatically; crossing a
process boundary, the wire tid field widens to ``tid=<tid>/<sid>`` so the
server's spans parent under the client RPC that caused them (the bare
``tid=<tid>`` form stays accepted, and servers echo the raw value so old
clients' exact-suffix unstamp keeps working).  ``obs/forensics.py``
assembles the per-process JSONL spills back into trees and diffs the
slow ones against the fast ones.

Wire compatibility is the hard constraint: the seed protocol's servers
validate field counts strictly (``len(parts) == 3`` etc.), so the tid
field is ONLY appended while a trace context is active — untraced traffic
stays byte-identical in both directions, and old servers never see the
extra field unless an operator opts a client in.

Context is thread-local because the serving stack is thread-per-connection
and the sharded clients fan out on pool threads; ``call_with_trace``
captures the submitting thread's tid so pool workers inherit it
explicitly (thread-locals do not cross ``ThreadPoolExecutor.submit``).

Event sinks, controlled by ``TPUMS_TRACE``:

- unset/``0`` — events still go to a small in-process ring buffer (cheap:
  one dict + deque append), which is what the in-process tests read;
- a path — additionally appended as JSONL to that file (``-`` = stderr),
  which is what ``scripts/chaos_kill.py`` and multi-process smoke runs
  use to correlate across processes.  The file sink rotates at
  ``TPUMS_TRACE_MAX_BYTES`` (keeping ``TPUMS_TRACE_KEEP`` old files) so a
  long soak cannot fill the disk.

``TPUMS_TRACE_SAMPLE`` (0..1) is the head-sampling knob: ``sample_trace``
rolls it once per would-be trace root, so span cost scales with the
sample rate, not the request rate.
"""

from __future__ import annotations

import json
import os
import random
import secrets
import sys
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from . import metrics as _metrics

TID_FIELD = "tid="
_RING_CAP = 4096
_DEFAULT_MAX_BYTES = 64 << 20
_DEFAULT_KEEP = 3

class _TraceLocal(threading.local):
    # Class-level defaults so the untraced read is a plain attribute hit:
    # getattr(local, "tid", None) on a thread that never traced otherwise
    # raises-and-catches AttributeError internally (~0.5us), and
    # current_trace()/current_span_id() run on every request's hot path.
    tid = None
    spans = None


_local = _TraceLocal()

# Cross-thread stage registry for the sampling profiler (obs/profiler.py).
# The span stack above is thread-LOCAL (only the owning thread can read
# it), but the profiler samples from its own timer thread, so spans
# additionally publish their stage *kind* here, keyed by thread ident.
# Mutation discipline: each thread touches only its own ident's list, the
# sampler only reads — under the GIL that makes the plain dict safe, and a
# rare torn read costs one mis-attributed sample, never corruption.  Cost
# rides the TRACED path only (span enter/exit); untraced requests never
# touch it.
_thread_stages: Dict[int, List[str]] = {}


def push_stage(kind: str) -> None:
    """Mark this thread as inside ``kind`` for the profiler's sampler.
    Span enter does this automatically; bare call sites (benches, the
    server dispatch choke point) may use ``profiler.prof_stage``."""
    ident = threading.get_ident()
    stack = _thread_stages.get(ident)
    if stack is None:
        stack = _thread_stages[ident] = []
    stack.append(kind)


def pop_stage() -> None:
    ident = threading.get_ident()
    stack = _thread_stages.get(ident)
    if stack:
        stack.pop()
        if not stack:
            _thread_stages.pop(ident, None)


def thread_stages() -> Dict[int, str]:
    """Sampler view: thread ident -> innermost active stage name.  Copies
    under the GIL; threads that are outside any stage are absent."""
    out: Dict[int, str] = {}
    for ident, stack in list(_thread_stages.items()):
        try:
            if stack:
                out[ident] = stack[-1]
        except IndexError:  # racing pop on the owner thread
            continue
    return out


_ring_lock = threading.Lock()
_ring: Deque[dict] = deque(maxlen=_RING_CAP)
_file_lock = threading.Lock()
_file_handle = None
_file_path_cached: Optional[str] = None
_file_bytes = 0
_file_max_bytes = _DEFAULT_MAX_BYTES


def new_trace_id() -> str:
    """16 hex chars — wide enough to never collide within a bench run,
    short enough to cost one small tab field on the wire."""
    return secrets.token_hex(8)


def new_span_id() -> str:
    """8 hex chars — unique within one trace, not globally."""
    return secrets.token_hex(4)


_sample_cache = ("", 0.0)  # (raw env string, parsed rate)


def trace_sample_rate() -> float:
    """``TPUMS_TRACE_SAMPLE`` clamped to [0, 1]; 0 when unset/garbage.
    Parsed once per distinct env value — workload drivers roll this per
    request root, so the steady-state cost is one dict lookup and a
    string compare, not a float parse (the 3% hot-path bar counts it)."""
    global _sample_cache
    raw = os.environ.get("TPUMS_TRACE_SAMPLE") or "0"
    cached_raw, cached = _sample_cache
    if raw is cached_raw or raw == cached_raw:
        return cached
    try:
        rate = max(0.0, min(1.0, float(raw)))
    except ValueError:
        rate = 0.0
    _sample_cache = (raw, rate)
    return rate


def sample_trace() -> Optional[str]:
    """Roll the sampling dice once: a fresh trace id with probability
    ``TPUMS_TRACE_SAMPLE``, else None.  Workload drivers and the update
    plane call this at trace-root points so span volume follows the knob
    instead of the request rate."""
    r = trace_sample_rate()
    if r <= 0.0:
        return None
    if r < 1.0 and random.random() >= r:
        return None
    return new_trace_id()


# ---------------------------------------------------------------------------
# thread-local context
# ---------------------------------------------------------------------------

def current_trace() -> Optional[str]:
    return getattr(_local, "tid", None)


def set_trace(tid: Optional[str]) -> Optional[str]:
    """Install ``tid`` as this thread's trace context -> previous value."""
    prev = getattr(_local, "tid", None)
    _local.tid = tid
    return prev


class trace_span:
    """``with trace_span() as tid:`` — installs a (fresh or given) trace id
    for the block and restores the previous context on exit."""

    __slots__ = ("tid", "_prev")

    def __init__(self, tid: Optional[str] = None):
        self.tid = tid or new_trace_id()
        self._prev = None

    def __enter__(self) -> str:
        self._prev = set_trace(self.tid)
        return self.tid

    def __exit__(self, *exc) -> None:
        set_trace(self._prev)


def current_span_id() -> Optional[str]:
    """Innermost open span on this thread, or None outside any span."""
    stack = getattr(_local, "spans", None)
    return stack[-1] if stack else None


def current_context() -> Optional[str]:
    """The value to hand ``call_with_trace`` when fanning out to a pool:
    ``tid/sid`` while a span is open (so the worker's spans parent under
    it), the bare tid otherwise, None when untraced."""
    tid = current_trace()
    if tid is None:
        return None
    sid = current_span_id()
    return f"{tid}/{sid}" if sid else tid


class span:
    """``with span("stage", op=...):`` — one timed node in the request's
    causal tree.  Allocates a span id, parents under the innermost open
    span on this thread, and emits a single event carrying
    ``sid``/``psid``/``t0``/``dur_s`` on exit.  A no-op (no id, no event)
    when no trace context is active, so instrumented code pays one
    thread-local read on the untraced path."""

    __slots__ = ("kind", "fields", "tid", "sid", "_psid", "_t0")

    def __init__(self, kind: str, tid: Optional[str] = None, **fields):
        self.kind = kind
        self.fields = fields
        self.tid = tid
        self.sid = None

    def __enter__(self) -> "span":
        tid = self.tid if self.tid is not None else current_trace()
        if tid is None:
            return self
        self.tid = tid
        self.sid = new_span_id()
        self._psid = current_span_id()
        stack = getattr(_local, "spans", None)
        if stack is None:
            stack = _local.spans = []
        stack.append(self.sid)
        push_stage(self.kind)
        self._t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.sid is None:
            return
        _local.spans.pop()
        pop_stage()
        if exc_type is not None:
            self.fields.setdefault("error", repr(exc))
        event(self.kind, tid=self.tid, sid=self.sid, psid=self._psid,
              t0=self._t0, dur_s=time.time() - self._t0, **self.fields)


def span_event(kind: str, tid: Optional[str] = None,
               dur_s: Optional[float] = None, t0: Optional[float] = None,
               sid: Optional[str] = None, psid: Optional[str] = None,
               **fields) -> Optional[dict]:
    """One-shot span record for call sites that already know the duration
    (client RPCs, server replies, synthesized microbatch stages).  None
    when untraced."""
    tid = tid if tid is not None else current_trace()
    if tid is None:
        return None
    return event(kind, tid=tid, sid=sid if sid is not None else new_span_id(),
                 psid=psid if psid is not None else current_span_id(),
                 t0=t0, dur_s=dur_s, **fields)


def call_with_trace(tid: Optional[str], fn: Callable, *args, **kwargs):
    """Run ``fn`` with ``tid`` installed — the pool-submit adapter used by
    the sharded/HA fan-out (``pool.submit(call_with_trace, tid, fn, ...)``)
    so worker threads inherit the submitting request's context.  ``tid``
    may be the composite ``tid/sid`` from ``current_context()``: the sid
    seeds the worker thread's span stack so its spans parent under the
    caller's open span."""
    if tid is None:
        return fn(*args, **kwargs)
    base, psid = split_tid(tid)
    prev = set_trace(base)
    prev_stack = getattr(_local, "spans", None)
    _local.spans = [psid] if psid else []
    try:
        return fn(*args, **kwargs)
    finally:
        set_trace(prev)
        _local.spans = prev_stack if prev_stack is not None else []


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------

def stamp(request: str, tid: Optional[str] = None) -> str:
    """Append ``\\ttid=<id>`` when a trace is active; otherwise return the
    request untouched (the byte-compatibility guarantee lives here)."""
    tid = tid if tid is not None else current_trace()
    if tid is None:
        return request
    return f"{request}\t{TID_FIELD}{tid}"


def unstamp_reply(reply: str, tid: str) -> str:
    """Strip the server's tid echo off a reply.  Only the exact suffix for
    the id we sent is removed, so payloads that legitimately contain tabs
    (MGET) cannot be corrupted."""
    suffix = f"\t{TID_FIELD}{tid}"
    if reply.endswith(suffix):
        return reply[: -len(suffix)]
    return reply


def pop_tid(parts: List[str]) -> Optional[str]:
    """Server side: remove and return a trailing ``tid=`` field from a
    split request line (mutates ``parts``); None when untraced.  The
    returned value is the RAW wire form — possibly ``tid/sid`` — so the
    server can echo it verbatim; split with ``split_tid``."""
    if len(parts) >= 2 and parts[-1].startswith(TID_FIELD):
        return parts.pop()[len(TID_FIELD):]
    return None


def wire_tid(tid: str, sid: Optional[str] = None) -> str:
    """The wire form of a trace context: ``tid/sid`` when the caller has
    an open span for this RPC, the bare tid otherwise."""
    return f"{tid}/{sid}" if sid else tid


def split_tid(raw: Optional[str]):
    """Split a raw wire tid into ``(trace_id, parent_span_id)`` — the
    parent is None for the bare pre-span form."""
    if raw and "/" in raw:
        base, _, psid = raw.partition("/")
        return base, (psid or None)
    return raw, None


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

def _trace_file() -> Optional[str]:
    v = os.environ.get("TPUMS_TRACE", "").strip()
    if v in ("", "0", "1"):
        return None
    return v


def trace_file_path() -> Optional[str]:
    """The active JSONL spill path (None when TPUMS_TRACE is off or the
    stderr sink ``-``) — where forensics should collect from."""
    p = _trace_file()
    return None if p == "-" else p


def event(kind: str, tid: Optional[str] = None, **fields) -> dict:
    """Record one structured event.  Always lands in the in-process ring;
    additionally appended as one JSON line to ``TPUMS_TRACE`` when that is
    a path.  Returns the event dict (chaos_kill prints it)."""
    ev: Dict = {"ts": time.time(),
                "tid": tid if tid is not None else current_trace(),
                "kind": kind}
    ev.update(fields)
    if "sid" in ev:
        # span record: count it so fleet_signals can rate the span volume
        _metrics.get_registry().counter("tpums_trace_spans_total").inc()
    elif "psid" not in ev:
        # point event inside an open span parents under it automatically,
        # so retries/fan-out markers land in the assembled tree
        psid = current_span_id()
        if psid is not None:
            ev["psid"] = psid
    with _ring_lock:
        _ring.append(ev)
    path = _trace_file()
    if path is not None:
        line = json.dumps(ev, separators=(",", ":"), default=str)
        if path == "-":
            print(line, file=sys.stderr)
        else:
            _append_line(path, line)
    return ev


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _append_line(path: str, line: str) -> None:
    global _file_handle, _file_path_cached, _file_bytes, _file_max_bytes
    with _file_lock:
        if _file_handle is None or _file_path_cached != path:
            if _file_handle is not None:
                try:
                    _file_handle.close()
                except OSError:
                    pass
            _file_handle = open(path, "a", buffering=1)
            _file_path_cached = path
            try:
                _file_bytes = os.path.getsize(path)
            except OSError:
                _file_bytes = 0
            # rotation knobs are read once per open — cheap appends, and a
            # test that re-points TPUMS_TRACE re-reads them naturally
            _file_max_bytes = _env_int("TPUMS_TRACE_MAX_BYTES",
                                       _DEFAULT_MAX_BYTES)
        if _file_bytes >= _file_max_bytes > 0:
            _rotate_locked(path)
        _file_handle.write(line + "\n")
        _file_bytes += len(line) + 1


def _rotate_locked(path: str) -> None:
    """Size-capped keep-K rotation: path -> path.1 -> ... -> path.K, the
    oldest dropped.  Caller holds ``_file_lock``."""
    global _file_handle, _file_bytes
    try:
        _file_handle.close()
    except OSError:
        pass
    keep = max(0, _env_int("TPUMS_TRACE_KEEP", _DEFAULT_KEEP))
    try:
        if keep == 0:
            os.remove(path)
        else:
            for i in range(keep - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            os.replace(path, f"{path}.1")
    except OSError:
        pass  # cross-process rotation race: the loser just keeps appending
    _file_handle = open(path, "a", buffering=1)
    _file_bytes = 0


def recent_events(tid: Optional[str] = None,
                  kind: Optional[str] = None) -> List[dict]:
    """Snapshot the ring buffer, optionally filtered by tid and/or kind —
    the in-process way to reconstruct a request chain."""
    with _ring_lock:
        evs = list(_ring)
    if tid is not None:
        evs = [e for e in evs if e.get("tid") == tid]
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


def clear_events() -> None:
    with _ring_lock:
        _ring.clear()


def load_events(path: str) -> List[dict]:
    """Parse a JSONL event file (cross-process correlation: chaos runs,
    obs_smoke).  Malformed lines are skipped, not fatal — the file is
    append-shared across processes."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def events_counter(kind: str, **labels) -> None:
    """Event + matching counter in one call — supervisor transitions use
    this so 'respawn happened' is both a countable series and a
    reconstructable timeline entry."""
    event(kind, **labels)
    _metrics.get_registry().counter(
        "tpums_events_total", kind=kind).inc()
