"""Dapper-style request tracing over the tab-separated wire protocol.

A trace is a 16-hex-char id that a client stamps onto a request as a
trailing ``tid=<id>`` tab field, the server echoes back, and every hop in
between (shard fan-out threads, HA failover retries, the microbatch
dispatcher) records against as structured **events**: one JSON object per
event with ``ts``/``tid``/``kind`` plus free-form span fields (queue wait,
batch size, device seconds).  Reconstructing one slow request end to end
is then a filter of the event log by tid.

Wire compatibility is the hard constraint: the seed protocol's servers
validate field counts strictly (``len(parts) == 3`` etc.), so the tid
field is ONLY appended while a trace context is active — untraced traffic
stays byte-identical in both directions, and old servers never see the
extra field unless an operator opts a client in.

Context is thread-local because the serving stack is thread-per-connection
and the sharded clients fan out on pool threads; ``call_with_trace``
captures the submitting thread's tid so pool workers inherit it
explicitly (thread-locals do not cross ``ThreadPoolExecutor.submit``).

Event sinks, controlled by ``TPUMS_TRACE``:

- unset/``0`` — events still go to a small in-process ring buffer (cheap:
  one dict + deque append), which is what the in-process tests read;
- a path — additionally appended as JSONL to that file (``-`` = stderr),
  which is what ``scripts/chaos_kill.py`` and multi-process smoke runs
  use to correlate across processes.
"""

from __future__ import annotations

import json
import os
import secrets
import sys
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from . import metrics as _metrics

TID_FIELD = "tid="
_RING_CAP = 4096

_local = threading.local()
_ring_lock = threading.Lock()
_ring: Deque[dict] = deque(maxlen=_RING_CAP)
_file_lock = threading.Lock()
_file_handle = None
_file_path_cached: Optional[str] = None


def new_trace_id() -> str:
    """16 hex chars — wide enough to never collide within a bench run,
    short enough to cost one small tab field on the wire."""
    return secrets.token_hex(8)


# ---------------------------------------------------------------------------
# thread-local context
# ---------------------------------------------------------------------------

def current_trace() -> Optional[str]:
    return getattr(_local, "tid", None)


def set_trace(tid: Optional[str]) -> Optional[str]:
    """Install ``tid`` as this thread's trace context -> previous value."""
    prev = getattr(_local, "tid", None)
    _local.tid = tid
    return prev


class trace_span:
    """``with trace_span() as tid:`` — installs a (fresh or given) trace id
    for the block and restores the previous context on exit."""

    __slots__ = ("tid", "_prev")

    def __init__(self, tid: Optional[str] = None):
        self.tid = tid or new_trace_id()
        self._prev = None

    def __enter__(self) -> str:
        self._prev = set_trace(self.tid)
        return self.tid

    def __exit__(self, *exc) -> None:
        set_trace(self._prev)


def call_with_trace(tid: Optional[str], fn: Callable, *args, **kwargs):
    """Run ``fn`` with ``tid`` installed — the pool-submit adapter used by
    the sharded/HA fan-out (``pool.submit(call_with_trace, tid, fn, ...)``)
    so worker threads inherit the submitting request's context."""
    if tid is None:
        return fn(*args, **kwargs)
    prev = set_trace(tid)
    try:
        return fn(*args, **kwargs)
    finally:
        set_trace(prev)


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------

def stamp(request: str, tid: Optional[str] = None) -> str:
    """Append ``\\ttid=<id>`` when a trace is active; otherwise return the
    request untouched (the byte-compatibility guarantee lives here)."""
    tid = tid if tid is not None else current_trace()
    if tid is None:
        return request
    return f"{request}\t{TID_FIELD}{tid}"


def unstamp_reply(reply: str, tid: str) -> str:
    """Strip the server's tid echo off a reply.  Only the exact suffix for
    the id we sent is removed, so payloads that legitimately contain tabs
    (MGET) cannot be corrupted."""
    suffix = f"\t{TID_FIELD}{tid}"
    if reply.endswith(suffix):
        return reply[: -len(suffix)]
    return reply


def pop_tid(parts: List[str]) -> Optional[str]:
    """Server side: remove and return a trailing ``tid=`` field from a
    split request line (mutates ``parts``); None when untraced."""
    if len(parts) >= 2 and parts[-1].startswith(TID_FIELD):
        return parts.pop()[len(TID_FIELD):]
    return None


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

def _trace_file() -> Optional[str]:
    v = os.environ.get("TPUMS_TRACE", "").strip()
    if v in ("", "0", "1"):
        return None
    return v


def event(kind: str, tid: Optional[str] = None, **fields) -> dict:
    """Record one structured event.  Always lands in the in-process ring;
    additionally appended as one JSON line to ``TPUMS_TRACE`` when that is
    a path.  Returns the event dict (chaos_kill prints it)."""
    ev: Dict = {"ts": time.time(),
                "tid": tid if tid is not None else current_trace(),
                "kind": kind}
    ev.update(fields)
    with _ring_lock:
        _ring.append(ev)
    path = _trace_file()
    if path is not None:
        line = json.dumps(ev, separators=(",", ":"), default=str)
        if path == "-":
            print(line, file=sys.stderr)
        else:
            _append_line(path, line)
    return ev


def _append_line(path: str, line: str) -> None:
    global _file_handle, _file_path_cached
    with _file_lock:
        if _file_handle is None or _file_path_cached != path:
            if _file_handle is not None:
                try:
                    _file_handle.close()
                except OSError:
                    pass
            _file_handle = open(path, "a", buffering=1)
            _file_path_cached = path
        _file_handle.write(line + "\n")


def recent_events(tid: Optional[str] = None,
                  kind: Optional[str] = None) -> List[dict]:
    """Snapshot the ring buffer, optionally filtered by tid and/or kind —
    the in-process way to reconstruct a request chain."""
    with _ring_lock:
        evs = list(_ring)
    if tid is not None:
        evs = [e for e in evs if e.get("tid") == tid]
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


def clear_events() -> None:
    with _ring_lock:
        _ring.clear()


def load_events(path: str) -> List[dict]:
    """Parse a JSONL event file (cross-process correlation: chaos runs,
    obs_smoke).  Malformed lines are skipped, not fatal — the file is
    append-shared across processes."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def events_counter(kind: str, **labels) -> None:
    """Event + matching counter in one call — supervisor transitions use
    this so 'respawn happened' is both a countable series and a
    reconstructable timeline entry."""
    event(kind, **labels)
    _metrics.get_registry().counter(
        "tpums_events_total", kind=kind).inc()
