"""Serving-plane observability: metrics registry, request tracing, fleet
scrape.

- ``obs.metrics`` — process-wide counters/gauges/log-bucket histograms,
  JSON snapshots, Prometheus exposition, snapshot merge/diff algebra.
- ``obs.tracing`` — wire-propagated trace ids (``tid=`` tab field),
  thread-local context, structured JSONL event log.
- ``obs.scrape`` — registry-driven fleet scrape + per-shard aggregation.
- ``obs.workload`` — open-loop zipfian mixed-verb traffic engine with
  coordinated-omission-safe recording + the closed-loop rehearsal driver.
- ``obs.slo`` — declarative per-verb objectives, error-budget burn rates,
  and the ``SLOReport`` artifact with event attribution.
- ``obs.tsdb`` — bounded ring time-series retention for the watch loop
  (rate/quantile/derivative queries over trailing windows).
- ``obs.rules`` — declarative alert rules: thresholds, absence,
  multi-window burn rate, ``for:`` hold-down, flap suppression.
- ``obs.watch`` — the continuous fleet watch loop + model-quality canary
  (``python -m flink_ms_tpu.obs.watch``).

Knobs: ``TPUMS_METRICS=0`` disables collection (observations become one
attribute check); ``TPUMS_TRACE=<path>`` mirrors events to a JSONL file
(``-`` = stderr) in addition to the in-process ring buffer;
``TPUMS_WATCH_*`` sizes the watch loop (see README "Fleet watch &
alerting").
"""

from .metrics import (  # noqa: F401
    LATENCY_BUCKETS_S,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    bucketed_quantiles,
    diff_snapshots,
    get_registry,
    log_buckets,
    merge_snapshots,
    metrics_enabled,
    render_prometheus,
    set_enabled,
    snapshot_quantile,
    snapshot_to_json_line,
    synthesize_requests,
)
from .tracing import (  # noqa: F401
    call_with_trace,
    clear_events,
    current_trace,
    event,
    events_counter,
    load_events,
    new_trace_id,
    pop_tid,
    recent_events,
    set_trace,
    stamp,
    trace_span,
    unstamp_reply,
)

# workload/slo/tsdb/rules/watch are intentionally NOT imported eagerly:
# they pull in the serving stack when actually driven.  Import them as
# submodules (``from flink_ms_tpu.obs import workload, slo, watch``).
