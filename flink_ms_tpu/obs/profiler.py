"""Continuous sampling profiler — the "which *code* burns the time" layer.

The forensics plane (obs/forensics.py) names the slow *stage* of a traced
request; this module names the slow *frames*, Google-Wide-Profiling
style: always-on, low-overhead, fleet-merged.

A timer-driven sampler thread walks ``sys._current_frames()`` at
``TPUMS_PROF_HZ`` (default ~47 Hz — deliberately co-prime with common
periodic work so the sampler cannot phase-lock with a 10/20/50 Hz loop)
and aggregates **folded stacks**: one key per unique call path,
``stage;mod.func;mod.func;...`` root→leaf, weighted by sample count.
The leading ``stage`` segment is the innermost active span stage on the
sampled thread (the PR-14 span stack publishes its stage kinds into a
cross-thread registry — ``tracing.thread_stages``), so a profile answers
"inside ``server_reply``, which frames burn the time?".  Threads outside
any span key under ``-``.

These are **CPU** profiles: a thread whose per-thread CPU clock
(``/proc/self/task/<tid>/stat``) did not advance since the previous tick
is parked (recv/sleep/poll) and is not counted — otherwise every idle
serving thread accrues samples at full hz and the hot frames drown.
``TPUMS_PROF_IDLE=1`` switches to wall-clock semantics (count every
live thread), which is also the automatic fallback where /proc is
unavailable.

Everything downstream treats a profile as a plain dict::

    {"ts": ..., "hz": 47.0, "samples": N, "wall_s": ..., "unit": "seconds",
     "stacks": {"stage;frame;frame": seconds, ...}, "meta": {...}}

with stack weights in SECONDS (count/hz on the Python plane; the native
plane reports its per-verb ``CLOCK_THREAD_CPUTIME_ID`` self-time directly
in seconds under synthetic ``native;<verb>`` stacks), so Python and C++
cost merge into one fleet profile: ``merge_profiles`` is an associative
fold (sum per-key seconds — exactly ``metrics.merge_snapshots``'s
discipline), and ``scrape.scrape_fleet_profiles`` applies it across every
registry endpoint's ``PROFILE`` verb.

Artifacts and scrapes:

- rotated folded-stack artifacts: when ``TPUMS_PROF_DIR`` is set, the
  sampler flushes ``profile.folded`` (keep-K rotation, ``TPUMS_PROF_KEEP``)
  every ``TPUMS_PROF_FLUSH_S`` seconds — flamegraph.pl-compatible
  collapsed format, one ``stack weight_us`` line each;
- the ``PROFILE`` wire verb (both server planes) ships the snapshot as
  one ``P\\t<json>`` line — the METRICS pattern applied to profiles;
- each flush also publishes ``tpums_prof_samples_total`` and the process
  CPU counter ``tpums_process_cpu_seconds_total`` into the metrics
  registry, which is what the watch plane's CPU rules alert on (and the
  alert page then carries ``profdiff``'s top-delta frames).

``TPUMS_PROF=0`` is the kill switch; the enforced hot-path bar is the
profiler arm of ``scripts/obs_overhead_ab.py`` (GET p50 overhead <= 3%,
ABAB).

CLI::

    python -m flink_ms_tpu.obs.profiler --flamegraph [FILE]  # folded text
    python -m flink_ms_tpu.obs.profiler --diff BASE CURRENT  # ranked delta
    python -m flink_ms_tpu.obs.profiler --fleet              # merged scrape
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["SamplingProfiler", "prof_stage", "prof_enabled", "prof_hz",
           "get_profiler", "ensure_started", "stop_profiler",
           "profiler_active",
           "merge_profiles", "profile_to_folded", "folded_to_profile",
           "load_profile", "parse_profile_reply", "scrape_profile",
           "CPU_SECONDS_SERIES", "SAMPLES_SERIES", "main"]

DEFAULT_HZ = 47.0
DEFAULT_FLUSH_S = 10.0
DEFAULT_KEEP = 3
DEFAULT_MAX_STACKS = 8192
DEFAULT_DEPTH = 48

ARTIFACT_NAME = "profile.folded"
UNTRACED_STAGE = "-"
OVERFLOW_KEY = UNTRACED_STAGE + ";(overflow)"

# series names shared with rules/watch/scrape — the CPU alert rule keys on
# the counter, and the alert page attaches profdiff's top frames to it
CPU_SECONDS_SERIES = "tpums_process_cpu_seconds_total"
SAMPLES_SERIES = "tpums_prof_samples_total"


def _env_float(name: str, default: float, lo: float) -> float:
    try:
        return max(float(os.environ.get(name, "") or default), lo)
    except ValueError:
        return default


def _env_int(name: str, default: int, lo: int) -> int:
    try:
        return max(int(os.environ.get(name, "") or default), lo)
    except ValueError:
        return default


def prof_enabled() -> bool:
    """``TPUMS_PROF=0`` is the kill switch; anything else (including
    unset) leaves the always-on profiler on."""
    return os.environ.get("TPUMS_PROF", "1").strip() != "0"


def prof_hz() -> float:
    return _env_float("TPUMS_PROF_HZ", DEFAULT_HZ, 1.0)


class prof_stage:
    """``with prof_stage("stage"):`` — mark this thread's samples with a
    stage name WITHOUT requiring an active trace (benches, workers, the
    server dispatch choke point).  Span enter/exit does the same thing
    implicitly for traced requests."""

    __slots__ = ("kind",)

    def __init__(self, kind: str):
        self.kind = kind

    def __enter__(self) -> "prof_stage":
        _tracing.push_stage(self.kind)
        return self

    def __exit__(self, *exc) -> None:
        _tracing.pop_stage()


def _thread_cpu_ticks(native_id: int) -> Optional[int]:
    """utime+stime jiffies for one kernel thread, USER_HZ granularity
    (``/proc/self/task/<tid>/stat`` fields 14+15 — parsed after the last
    ``)`` because comm may contain anything).  None when /proc is absent;
    the sampler then falls back to wall-clock semantics for that thread."""
    try:
        with open(f"/proc/self/task/{native_id}/stat", "rb") as f:
            data = f.read()
    except OSError:
        return None
    try:
        tail = data.rpartition(b")")[2].split()
        return int(tail[11]) + int(tail[12])
    except (ValueError, IndexError):
        return None


def _frame_name(frame) -> str:
    mod = frame.f_globals.get("__name__", "?")
    return f"{mod}.{frame.f_code.co_name}"


def _fold(frame, depth: int) -> str:
    """Fold one thread's live frame chain into ``root;...;leaf``."""
    names: List[str] = []
    while frame is not None and len(names) < depth:
        names.append(_frame_name(frame))
        frame = frame.f_back
    names.reverse()
    return ";".join(names)


def _process_cpu_s() -> float:
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_utime + ru.ru_stime


class SamplingProfiler:
    """The always-on sampler.  One daemon thread; every period it walks
    ``sys._current_frames()`` (its own thread excluded), keys each
    thread's folded stack by the thread's active span stage, and bumps
    the count.  ``snapshot()`` converts counts to seconds (count/hz) —
    the cross-plane unit."""

    def __init__(self, hz: Optional[float] = None,
                 artifact_dir: Optional[str] = None,
                 flush_s: Optional[float] = None):
        self.hz = prof_hz() if hz is None else max(float(hz), 1.0)
        self.artifact_dir = (
            artifact_dir if artifact_dir is not None
            else (os.environ.get("TPUMS_PROF_DIR", "").strip() or None))
        self.flush_s = (_env_float("TPUMS_PROF_FLUSH_S", DEFAULT_FLUSH_S,
                                   0.05)
                        if flush_s is None else max(float(flush_s), 0.05))
        self.max_stacks = _env_int("TPUMS_PROF_MAX_STACKS",
                                   DEFAULT_MAX_STACKS, 16)
        self.depth = _env_int("TPUMS_PROF_DEPTH", DEFAULT_DEPTH, 4)
        # CPU profile semantics: a thread whose per-thread CPU clock did
        # not advance since the previous tick is parked (recv, sleep,
        # poll) and is NOT counted — otherwise every idle serving thread
        # accrues samples at full hz and drowns the hot frames.
        # TPUMS_PROF_IDLE=1 switches to wall-clock (count everything).
        self.include_idle = (
            os.environ.get("TPUMS_PROF_IDLE", "0").strip() == "1")
        self._cpu_ticks: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._stacks: Dict[str, int] = {}
        self.samples = 0          # thread-samples accumulated
        self.ticks = 0            # sampler wakeups
        self.started_at: Optional[float] = None
        self._published_samples = 0
        self._published_cpu = _process_cpu_s()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ---------------------------------------------------------

    def sample_once(self) -> int:
        """One synchronous sampling pass -> threads sampled.  Public so
        tests can pin attribution deterministically (no timer race)."""
        me = threading.get_ident()
        sampler_ident = (self._thread.ident
                         if self._thread is not None else None)
        stages = _tracing.thread_stages()
        natives: Dict[int, int] = {}
        if not self.include_idle:
            for t in threading.enumerate():
                if t.ident is not None and t.native_id is not None:
                    natives[t.ident] = t.native_id
        frames = sys._current_frames()
        n = 0
        with self._lock:
            for ident, frame in frames.items():
                if ident == me or ident == sampler_ident:
                    continue
                if not self.include_idle:
                    nid = natives.get(ident)
                    ticks = (_thread_cpu_ticks(nid)
                             if nid is not None else None)
                    if ticks is not None:
                        prev = self._cpu_ticks.get(ident)
                        self._cpu_ticks[ident] = ticks
                        if prev is not None and ticks <= prev:
                            continue    # no CPU burned since last tick
                stage = stages.get(ident, UNTRACED_STAGE)
                key = stage + ";" + _fold(frame, self.depth)
                if key not in self._stacks and \
                        len(self._stacks) >= self.max_stacks:
                    key = OVERFLOW_KEY
                self._stacks[key] = self._stacks.get(key, 0) + 1
                n += 1
            self.samples += n
            self.ticks += 1
            if len(self._cpu_ticks) > 2 * len(frames) + 64:
                self._cpu_ticks = {i: v for i, v in self._cpu_ticks.items()
                                   if i in frames}   # drop dead threads
        # help the GC: the frames dict pins every thread's live frame
        del frames
        return n

    def _run(self) -> None:
        period = 1.0 / self.hz
        next_t = time.monotonic() + period
        last_flush = time.monotonic()
        while not self._stop.is_set():
            delay = next_t - time.monotonic()
            if delay > 0:
                self._stop.wait(delay)
                if self._stop.is_set():
                    break
            next_t += period
            now = time.monotonic()
            if next_t < now:       # fell behind (suspend, 1-core squeeze):
                next_t = now + period  # re-anchor, don't burst-catch-up
            try:
                self.sample_once()
            except Exception:      # sampling must never kill the process
                pass
            if now - last_flush >= self.flush_s:
                last_flush = now
                try:
                    self.flush()
                except Exception:
                    pass

    # -- lifecycle --------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        self._stop.clear()
        self.started_at = time.time()
        self._published_cpu = _process_cpu_s()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpums-profiler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        try:
            self.flush()
        except Exception:
            pass

    # -- snapshots / artifacts --------------------------------------------

    def snapshot(self, meta: Optional[dict] = None) -> dict:
        """The profile dict (stack weights in seconds).  Associatively
        mergeable via ``merge_profiles``."""
        with self._lock:
            stacks = dict(self._stacks)
            samples = self.samples
        scale = 1.0 / self.hz
        return {
            "ts": time.time(),
            "hz": self.hz,
            "enabled": self.running,
            "samples": samples,
            "wall_s": (round(time.time() - self.started_at, 3)
                       if self.started_at else 0.0),
            "unit": "seconds",
            "stacks": {k: round(c * scale, 6) for k, c in stacks.items()},
            "meta": dict(meta or {}),
        }

    def clear(self) -> None:
        with self._lock:
            self._stacks.clear()
            self.samples = 0
            self.ticks = 0
        self.started_at = time.time()

    def flush(self) -> None:
        """Publish registry counters + (when configured) rotate out the
        folded artifact.  Called on the sampler's flush cadence and on
        ``stop()``."""
        reg = _metrics.get_registry()
        with self._lock:
            samples = self.samples
            distinct = len(self._stacks)
        delta = samples - self._published_samples
        if delta > 0:
            reg.counter(SAMPLES_SERIES).inc(delta)
            self._published_samples = samples
        cpu = _process_cpu_s()
        cpu_delta = cpu - self._published_cpu
        if cpu_delta > 0:
            reg.counter(CPU_SECONDS_SERIES).inc(cpu_delta)
            self._published_cpu = cpu
        reg.gauge("tpums_prof_distinct_stacks").set(distinct)
        if self.artifact_dir:
            self._write_artifact()

    def _write_artifact(self) -> None:
        os.makedirs(self.artifact_dir, exist_ok=True)
        path = os.path.join(self.artifact_dir, ARTIFACT_NAME)
        keep = _env_int("TPUMS_PROF_KEEP", DEFAULT_KEEP, 0)
        # keep-K rotation (the tracing spill's discipline): the newest
        # complete snapshot is always ARTIFACT_NAME, older flushes age
        # through .1 .. .K
        if os.path.exists(path):
            if keep == 0:
                try:
                    os.remove(path)
                except OSError:
                    pass
            else:
                for i in range(keep - 1, 0, -1):
                    src = f"{path}.{i}"
                    if os.path.exists(src):
                        try:
                            os.replace(src, f"{path}.{i + 1}")
                        except OSError:
                            pass
                try:
                    os.replace(path, f"{path}.1")
                except OSError:
                    pass
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(profile_to_folded(self.snapshot()))
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# module-global profiler (the serving stack's shared instance)
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global: Optional[SamplingProfiler] = None


def get_profiler() -> Optional[SamplingProfiler]:
    return _global


def profiler_active() -> bool:
    """Hot-path guard: is the process profiler collecting right now?
    Call sites that mark stages per-request (the server dispatch choke
    point) gate on this so the profiler-off configuration pays one
    module-global read, nothing more."""
    prof = _global
    return prof is not None and prof._thread is not None


def ensure_started() -> Optional[SamplingProfiler]:
    """Start (or return) the process-wide profiler; None when the
    ``TPUMS_PROF=0`` kill switch is set.  Idempotent — every ServingJob/
    EdgeProxy start funnels through here, first caller wins."""
    global _global
    if not prof_enabled():
        return None
    with _global_lock:
        if _global is None:
            _global = SamplingProfiler()
        if not _global.running:
            _global.start()
        return _global


def stop_profiler() -> None:
    """Stop and drop the process-wide profiler (tests)."""
    global _global
    with _global_lock:
        prof, _global = _global, None
    if prof is not None:
        prof.stop()


# ---------------------------------------------------------------------------
# profile algebra: merge / folded round-trip / wire form
# ---------------------------------------------------------------------------

def merge_profiles(profiles: Sequence[dict]) -> dict:
    """Associative fold over profile dicts: per-key seconds and sample
    counts SUM, ``ts`` is the newest, ``wall_s`` the longest, ``hz`` kept
    when uniform (0 marks a mixed/merged-plane profile — native entries
    carry no sampling rate).  Exactly ``merge_snapshots``'s stance:
    merge(merge(a,b),c) == merge(a,merge(b,c)) key-for-key."""
    stacks: Dict[str, float] = {}
    samples = 0
    ts = 0.0
    wall = 0.0
    hzs = set()
    planes: List[str] = []
    for p in profiles:
        if not isinstance(p, dict):
            continue
        for k, v in (p.get("stacks") or {}).items():
            stacks[k] = round(stacks.get(k, 0.0) + float(v), 6)
        samples += int(p.get("samples") or 0)
        ts = max(ts, float(p.get("ts") or 0.0))
        wall = max(wall, float(p.get("wall_s") or 0.0))
        hzs.add(float(p.get("hz") or 0.0))
        mp = p.get("meta") or {}
        if mp.get("plane"):
            planes.append(str(mp["plane"]))
        # merged profiles carry "planes" (plural) — propagate so the
        # fold stays associative over already-merged inputs
        planes.extend(str(x) for x in (mp.get("planes") or []))
    return {
        "ts": ts,
        "hz": hzs.pop() if len(hzs) == 1 else 0.0,
        "samples": samples,
        "wall_s": wall,
        "unit": "seconds",
        "stacks": stacks,
        "meta": {"merged": len([p for p in profiles
                                if isinstance(p, dict)]),
                 "planes": sorted(set(planes))},
    }


def profile_to_folded(profile: dict) -> str:
    """flamegraph.pl collapsed format: ``stack weight`` per line, weight
    in integer MICROSECONDS (the folded format wants integers; at 47 Hz a
    single sample is ~21277 us, so nothing truncates to zero)."""
    lines = []
    for key in sorted(profile.get("stacks") or {}):
        us = int(round(float(profile["stacks"][key]) * 1e6))
        if us > 0:
            lines.append(f"{key} {us}")
    return "\n".join(lines) + ("\n" if lines else "")


def folded_to_profile(text: str) -> dict:
    """Parse collapsed format back to a profile dict (seconds)."""
    stacks: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, weight = line.rpartition(" ")
        if not stack:
            continue
        try:
            us = float(weight)
        except ValueError:
            continue
        stacks[stack] = round(stacks.get(stack, 0.0) + us / 1e6, 6)
    return {"ts": 0.0, "hz": 0.0, "samples": 0, "wall_s": 0.0,
            "unit": "seconds", "stacks": stacks, "meta": {}}


def load_profile(path: str) -> dict:
    """Read a profile artifact: JSON (a snapshot dict, possibly the
    ``P\\t`` wire line) or folded text — both load to the same shape."""
    with open(path) as f:
        text = f.read()
    stripped = text.strip()
    if stripped.startswith("P\t"):
        stripped = stripped[2:]
    if stripped.startswith("{"):
        doc = json.loads(stripped)
        if not isinstance(doc, dict) or "stacks" not in doc:
            raise ValueError(f"{path}: not a profile JSON")
        return doc
    return folded_to_profile(text)


def parse_profile_reply(line: str) -> Optional[dict]:
    """``P\\t<json>`` -> profile dict, None on anything else (old servers
    answer ``E\\tbad request`` — a fleet scrape treats that as 'plane has
    no profiler', not an error)."""
    if not line.startswith("P\t"):
        return None
    try:
        doc = json.loads(line[2:])
    except ValueError:
        return None
    return doc if isinstance(doc, dict) and "stacks" in doc else None


def scrape_profile(host: str, port: int, timeout_s: float = 2.0
                   ) -> Optional[dict]:
    """One PROFILE round-trip (the METRICS scrape pattern — raw tab
    socket, one line back)."""
    import socket

    host = host or "localhost"
    if host == "0.0.0.0":
        host = "localhost"
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout_s) as sock:
            sock.sendall(b"PROFILE\n")
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(1 << 16)
                if not chunk:
                    break
                buf += chunk
    except OSError:
        return None
    return parse_profile_reply(buf.decode("utf-8", "replace").strip())


def profile_reply_line(meta: Optional[dict] = None) -> str:
    """The server side of the PROFILE verb: the process profiler's
    snapshot as one ``P\\t<json>`` line.  With the profiler off/killed the
    reply still parses (enabled false, empty stacks) so round-trip parity
    holds in every configuration."""
    prof = _global
    if prof is not None:
        snap = prof.snapshot(meta=meta)
    else:
        snap = {"ts": time.time(), "hz": prof_hz(), "enabled": False,
                "samples": 0, "wall_s": 0.0, "unit": "seconds",
                "stacks": {}, "meta": dict(meta or {})}
    return "P\t" + json.dumps(snap, separators=(",", ":"), default=str)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _top_lines(profile: dict, n: int = 20) -> Iterable[str]:
    total = sum(profile.get("stacks", {}).values()) or 1.0
    rows = sorted(profile.get("stacks", {}).items(),
                  key=lambda kv: -kv[1])[:n]
    for key, s in rows:
        yield f"{100.0 * s / total:6.2f}%  {s:10.4f}s  {key}"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flink_ms_tpu.obs.profiler",
        description="continuous profiling plane: folded stacks, fleet "
                    "merge, regression diff")
    ap.add_argument("--flamegraph", nargs="?", const="-", metavar="FILE",
                    help="render FILE (JSON or folded; default: scrape "
                         "the live fleet) as collapsed folded stacks")
    ap.add_argument("--diff", nargs=2, metavar=("BASE", "CURRENT"),
                    help="rank frames by delta-share between two profile "
                         "artifacts (obs/profdiff.py)")
    ap.add_argument("--fleet", action="store_true",
                    help="scrape every registry endpoint's PROFILE verb "
                         "and print the merged profile")
    ap.add_argument("--json", action="store_true",
                    help="emit JSON instead of human-readable text")
    ap.add_argument("--out", metavar="FILE",
                    help="also write the result (folded for profiles, "
                         "JSON for diffs) to FILE")
    args = ap.parse_args(argv)

    if args.diff:
        from . import profdiff
        base = load_profile(args.diff[0])
        cur = load_profile(args.diff[1])
        rep = profdiff.diff_profiles(base, cur)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rep, f, indent=2)
        if args.json:
            print(json.dumps(rep, indent=2))
        else:
            print(f"# gap {rep['gap_s']:+.4f}s "
                  f"(base {rep['base_total_s']:.4f}s -> "
                  f"current {rep['cur_total_s']:.4f}s)")
            for row in rep["frames"][:20]:
                print(f"{100.0 * row['delta_share']:6.1f}%  "
                      f"{row['delta_s']:+10.4f}s  {row['frame']}")
        return 0

    if args.fleet or args.flamegraph == "-" or args.flamegraph is None:
        from .scrape import scrape_fleet_profiles
        result = scrape_fleet_profiles()
        profile = result["fleet"]
        if not result["scraped"]:
            print("no PROFILE-speaking replicas in the registry",
                  file=sys.stderr)
    else:
        profile = load_profile(args.flamegraph)

    folded = profile_to_folded(profile)
    if args.out:
        with open(args.out, "w") as f:
            f.write(folded)
    if args.json:
        print(json.dumps(profile, indent=2, default=str))
    elif args.flamegraph is not None:
        sys.stdout.write(folded)
    else:
        print(f"# {profile.get('samples', 0)} samples, "
              f"{len(profile.get('stacks', {}))} stacks, "
              f"wall {profile.get('wall_s', 0)}s")
        for line in _top_lines(profile):
            print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
