"""Profile regression diff — forensics' stage ranking applied to frames.

``forensics.diff_slow_fast`` splits traces into slow/fast sets and ranks
*stages* by ``delta_s`` with ``delta_share = delta / gap``; this module
applies the identical discipline to two *profiles* (base vs current
snapshots from ``obs/profiler.py``): per-frame SELF-time — the leaf
frame of each folded stack owns that stack's seconds — is totalled per
set, frames are ranked by the delta, and each row carries its share of
the total regression gap.  The watch plane attaches the top rows to
CPU-regression and quantile pages (``profile_top_frames``), closing the
chain *alert → stage (forensics) → frames (profdiff)*.

Self-time is deliberately frame-keyed, not stack-keyed: a function that
got hot shows ONE row regardless of how many call paths reach it, which
is what a pager wants.  Per-stage attribution survives in
``by_stage`` for the drill-down.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["self_times", "diff_profiles", "top_frames", "format_diff"]


def self_times(profile: dict) -> Dict[str, float]:
    """Fold a profile's stacks to per-frame SELF seconds: the leaf frame
    of each ``stage;frame;...;leaf`` key owns the full weight.  A bare
    one-segment key (shouldn't happen, but artifacts are hand-editable)
    self-attributes to itself."""
    out: Dict[str, float] = {}
    for key, s in (profile.get("stacks") or {}).items():
        leaf = key.rsplit(";", 1)[-1]
        out[leaf] = out.get(leaf, 0.0) + float(s)
    return out


def _stage_self_times(profile: dict) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for key, s in (profile.get("stacks") or {}).items():
        parts = key.split(";")
        stage = parts[0] if len(parts) > 1 else "-"
        leaf = parts[-1]
        per = out.setdefault(stage, {})
        per[leaf] = per.get(leaf, 0.0) + float(s)
    return out


def diff_profiles(base: dict, cur: dict,
                  min_delta_s: float = 0.0) -> dict:
    """Rank frames by self-time delta between two profiles.

    Returns::

        {"base_total_s", "cur_total_s", "gap_s",
         "frames": [{"frame", "base_self_s", "cur_self_s", "delta_s",
                     "delta_share"}, ...],   # delta-ranked, worst first
         "by_stage": {stage: [same rows], ...}}

    ``delta_share`` is each frame's fraction of the total regression gap
    (``cur_total - base_total``), exactly as forensics shares the
    slow-fast gap across stages.  When the totals shrank or held flat
    the share denominator falls back to the largest single positive
    delta, so "what grew most" still ranks sanely."""
    b = self_times(base)
    c = self_times(cur)
    base_total = sum(b.values())
    cur_total = sum(c.values())
    gap = cur_total - base_total
    deltas = {f: c.get(f, 0.0) - b.get(f, 0.0) for f in set(b) | set(c)}
    denom = gap if gap > 1e-12 else max(
        [d for d in deltas.values() if d > 0.0], default=1e-12)
    frames = []
    for f, d in deltas.items():
        if abs(d) < min_delta_s and min_delta_s > 0.0:
            continue
        frames.append({"frame": f,
                       "base_self_s": round(b.get(f, 0.0), 9),
                       "cur_self_s": round(c.get(f, 0.0), 9),
                       "delta_s": round(d, 9),
                       "delta_share": round(d / denom, 4)})
    frames.sort(key=lambda r: -r["delta_s"])

    by_stage: Dict[str, List[dict]] = {}
    sb = _stage_self_times(base)
    sc = _stage_self_times(cur)
    for stage in set(sb) | set(sc):
        pb, pc = sb.get(stage, {}), sc.get(stage, {})
        rows = []
        for f in set(pb) | set(pc):
            d = pc.get(f, 0.0) - pb.get(f, 0.0)
            rows.append({"frame": f,
                         "base_self_s": round(pb.get(f, 0.0), 9),
                         "cur_self_s": round(pc.get(f, 0.0), 9),
                         "delta_s": round(d, 9),
                         "delta_share": round(d / denom, 4)})
        rows.sort(key=lambda r: -r["delta_s"])
        by_stage[stage] = rows

    return {"base_total_s": round(base_total, 9),
            "cur_total_s": round(cur_total, 9),
            "gap_s": round(gap, 9),
            "frames": frames,
            "by_stage": by_stage}


def top_frames(base: dict, cur: dict, n: int = 5) -> List[dict]:
    """The page attachment: the ``n`` worst-regressing frames, positive
    deltas only (a frame that got CHEAPER never explains a CPU page)."""
    rep = diff_profiles(base, cur)
    return [r for r in rep["frames"] if r["delta_s"] > 0][:n]


def format_diff(rep: dict, n: int = 10) -> str:
    lines = [f"profile diff: total {rep['base_total_s']:.4f}s -> "
             f"{rep['cur_total_s']:.4f}s (gap {rep['gap_s']:+.4f}s)"]
    for i, row in enumerate(rep["frames"][:n], 1):
        if row["delta_s"] <= 0:
            break
        lines.append(
            f"  #{i} {row['frame']}: {row['delta_s'] * 1e3:+.1f}ms "
            f"({row['delta_share'] * 100:.0f}% of the gap; "
            f"{row['base_self_s'] * 1e3:.1f}ms -> "
            f"{row['cur_self_s'] * 1e3:.1f}ms self)")
    return "\n".join(lines)
