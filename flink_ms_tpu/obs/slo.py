"""Declarative SLOs + error-budget accounting over the fleet scrape.

The serving plane already exposes one shared latency ladder
(``metrics.LATENCY_BUCKETS_S``) from both ends: the workload engine's
client-side histograms (``obs/workload.py``) and every worker's
``tpums_server_latency_seconds`` reached through the fleet scrape.  This
module turns those raw series into the artifact an operator gates a
deploy on:

- ``SLOObjective`` / ``SLOSpec``   per-verb targets: availability, p99
  latency, max error-budget burn rate, goodput under shed.
- ``burn_rate``                    error-budget math: observed error rate
  over the budget (1 - availability target); 1.0 = burning exactly the
  budget, 14.4 = the classic "page now" multi-window threshold.
- ``verb_windows``                 per-verb request/error/latency deltas
  between two fleet merges (``diff_snapshots`` semantics, verb-labelled).
- ``build_report``                 the ``SLOReport`` JSON: per-verb
  measurements vs objectives, windowed burn rates over the scrape
  samples, a timeline, and attribution — every error sample and every
  breached objective is matched to the event that explains it (chaos
  kill, elastic cutover, correlated burst, failover); what cannot be
  matched is surfaced as ``unattributed``.
- ``human_summary`` / ``validate_report``  operator text + schema check.

The report's ``p99_ms`` is the coordinated-omission-safe client statistic
(latency from *intended* send); ``service_p99_ms`` (actual send -> reply)
is the series comparable to the fleet-scraped server percentile, and the
report carries the bucket-index distance between the two
(``p99_bucket_delta``; 0 or 1 = client and fleet agree within one bucket
of the shared ladder).
"""

from __future__ import annotations

import bisect
import math
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics as obs_metrics

__all__ = [
    "SLOObjective", "SLOSpec", "burn_rate", "verb_windows", "bucket_index",
    "build_report", "human_summary", "validate_report", "SCHEMA",
]

SCHEMA = "tpums.slo_report/1"

# client verb -> the server-side verb label its wire traffic lands on
# (client TOPK/TOPKV resolve factors via MGET then stream TOPKV; UPDATE is
# a journal write — no server query verb at all)
CLIENT_TO_SERVER_VERB: Dict[str, Optional[str]] = {
    "GET": "GET", "MGET": "MGET", "TOPK": "TOPKV", "TOPKV": "TOPKV",
    "UPDATE": None,
}

# event kinds that can legitimately explain an excursion
DISRUPTIVE_KINDS = frozenset({
    "rehearsal_kill", "chaos_kill", "chaos_kill_warming",
    # control-plane kill (the autopilot chaos arm): attributable like any
    # kill, but deliberately NOT in watch.KILL_KINDS — the contract under
    # test is that killing the controller must NOT page, so detection
    # latency is meaningless for it
    "chaos_kill_controller",
    "chaos_teardown",
    "elastic_scale_start", "elastic_cutover", "elastic_drained",
    "elastic_scale_abort", "generation_swap", "failover",
    "replica_respawn", "autoscale_decision",
    # model rollout protocol (serve/rollout.py — the elastic cutover
    # kinds under the rollout controller's event prefix)
    "rollout_scale_start", "rollout_cutover", "rollout_drained",
    "rollout_scale_abort", "rollout_verified", "rollout_rollback",
    # edge proxy tier (serve/edge.py): a fired hedge, an edge-side
    # admission shed and a client rotating to a surviving proxy are all
    # deliberate tail/failure management — attributable, never paged as
    # unexplained
    "edge_hedge", "edge_shed", "proxy_reconnect",
})

DEFAULT_ATTRIBUTION_WINDOW_S = 5.0

# an admission-shed request errors with this marker in the reply
# (serve/admission.py SHED_REPLY — string-matched here rather than
# imported so the obs layer stays importable without the serving stack).
# Sheds attribute to a synthetic ``admission_shed`` cause: deliberate
# policy, never an unexplained failure.
ADMISSION_SHED_MARKER = "over quota"


@dataclass(frozen=True)
class SLOObjective:
    """Targets for one verb; ``None`` disables that dimension."""
    verb: str
    availability: Optional[float] = 0.999
    p99_ms: Optional[float] = None
    burn_rate_max: Optional[float] = None
    goodput_min: Optional[float] = None

    def to_dict(self) -> dict:
        return {"verb": self.verb, "availability": self.availability,
                "p99_ms": self.p99_ms, "burn_rate_max": self.burn_rate_max,
                "goodput_min": self.goodput_min}

    @classmethod
    def from_dict(cls, d: dict) -> "SLOObjective":
        return cls(verb=d["verb"],
                   availability=d.get("availability"),
                   p99_ms=d.get("p99_ms"),
                   burn_rate_max=d.get("burn_rate_max"),
                   goodput_min=d.get("goodput_min"))


class SLOSpec:
    """A set of per-verb objectives."""

    def __init__(self, objectives: Sequence[SLOObjective]):
        self.objectives = tuple(objectives)
        self._by_verb = {o.verb: o for o in self.objectives}

    def for_verb(self, verb: str) -> Optional[SLOObjective]:
        return self._by_verb.get(verb)

    def to_dict(self) -> dict:
        return {"objectives": [o.to_dict() for o in self.objectives]}

    @classmethod
    def from_dict(cls, d: dict) -> "SLOSpec":
        return cls([SLOObjective.from_dict(o)
                    for o in d.get("objectives", [])])

    # per-verb defaults: point reads are held tight, fan-out scoring gets
    # a looser latency budget, writes are availability-only (their latency
    # is a local journal append)
    _DEFAULTS = {
        "GET": dict(availability=0.999, p99_ms=50.0, burn_rate_max=2.0,
                    goodput_min=0.99),
        "MGET": dict(availability=0.999, p99_ms=75.0, burn_rate_max=2.0,
                     goodput_min=0.99),
        "TOPK": dict(availability=0.995, p99_ms=250.0, burn_rate_max=2.0,
                     goodput_min=0.99),
        "TOPKV": dict(availability=0.995, p99_ms=250.0, burn_rate_max=2.0,
                      goodput_min=0.99),
        "UPDATE": dict(availability=0.999, p99_ms=None, burn_rate_max=2.0,
                       goodput_min=0.99),
    }

    @classmethod
    def default(cls, verbs: Sequence[str]) -> "SLOSpec":
        return cls([SLOObjective(verb=v, **cls._DEFAULTS.get(
            v, dict(availability=0.999, p99_ms=None,
                    burn_rate_max=2.0, goodput_min=0.99)))
            for v in verbs])


def burn_rate(requests: float, errors: float,
              availability_target: Optional[float]) -> Optional[float]:
    """Observed error rate as a multiple of the error budget: 1.0 burns
    the budget exactly at target pace; >1 exhausts it early."""
    if not requests or availability_target is None:
        return None
    budget = 1.0 - availability_target
    if budget <= 0:
        return None
    return (errors / requests) / budget


def bucket_index(v_s: Optional[float],
                 bounds: Sequence[float] = obs_metrics.LATENCY_BUCKETS_S
                 ) -> Optional[int]:
    """Which ladder bucket a latency falls in (None for missing/nan)."""
    if v_s is None or (isinstance(v_s, float) and math.isnan(v_s)):
        return None
    return bisect.bisect_left(list(bounds), v_s)


def _series_by_verb(snapshot: dict, kind: str, name: str) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for e in snapshot.get(kind, []):
        if e["name"] == name:
            verb = e.get("labels", {}).get("verb")
            if verb is not None:
                out[verb] = e
    return out


def verb_windows(before: dict, after: dict,
                 hist_name: str = "tpums_server_latency_seconds",
                 errors_name: str = "tpums_server_errors_total"
                 ) -> Dict[str, dict]:
    """Per-verb deltas between two fleet merges::

        {verb: {"requests", "errors", "hist": delta-hist-entry|None}}

    ``requests`` comes off the latency histogram's count (every request
    observes exactly once — same invariant ``synthesize_requests`` uses);
    ``hist`` is the bucket-wise delta, quantile-able via
    ``snapshot_quantile``."""
    b_h = _series_by_verb(before, "histograms", hist_name)
    a_h = _series_by_verb(after, "histograms", hist_name)
    b_e = _series_by_verb(before, "counters", errors_name)
    a_e = _series_by_verb(after, "counters", errors_name)
    out: Dict[str, dict] = {}
    for verb, h in a_h.items():
        prev = b_h.get(verb, {"counts": [0] * len(h["counts"]),
                              "count": 0, "sum": 0.0})
        dc = h["count"] - prev["count"]
        hist = None
        if dc > 0:
            hist = {"name": h["name"], "labels": dict(h.get("labels", {})),
                    "le": list(h["le"]),
                    "counts": [a - b for a, b in
                               zip(h["counts"], prev["counts"])],
                    "count": dc, "sum": h["sum"] - prev["sum"]}
        errs = (a_e.get(verb, {}).get("value", 0)
                - b_e.get(verb, {}).get("value", 0))
        if dc > 0 or errs:
            out[verb] = {"requests": max(dc, 0), "errors": max(errs, 0),
                         "hist": hist}
    return out


def _attribute_time(ts: float, timeline: Sequence[dict],
                    phases: Sequence[dict],
                    window_s: float) -> Optional[dict]:
    """The event that explains an excursion at wall time ``ts``: the
    nearest disruptive timeline event within +/- ``window_s`` (an error
    can precede its cutover/recovery event, so the window is symmetric),
    else the burst phase covering ``ts``."""
    best, best_dt = None, None
    for e in timeline:
        if e.get("kind") not in DISRUPTIVE_KINDS:
            continue
        dt = abs(ts - e.get("ts", 0.0))
        if dt <= window_s and (best_dt is None or dt < best_dt):
            best, best_dt = e, dt
    if best is not None:
        return {"kind": best["kind"], "ts": best.get("ts"),
                "dt_s": round(best_dt, 3)}
    for p in phases:
        if "burst" in p.get("name", "") and \
                p.get("t_start", 0) - window_s <= ts <= \
                p.get("t_end", 0) + window_s:
            return {"kind": "workload_phase", "phase": p["name"],
                    "ts": p.get("t_start"), "dt_s": 0.0}
    return None


def _client_verb_series(recorder_snapshot: dict) -> Dict[str, dict]:
    lat = _series_by_verb(recorder_snapshot, "histograms",
                          "tpums_client_latency_seconds")
    svc = _series_by_verb(recorder_snapshot, "histograms",
                          "tpums_client_service_seconds")
    req = _series_by_verb(recorder_snapshot, "counters",
                          "tpums_client_requests_total")
    err = _series_by_verb(recorder_snapshot, "counters",
                          "tpums_client_errors_total")
    out: Dict[str, dict] = {}
    for verb in sorted(set(lat) | set(req)):
        out[verb] = {
            "latency": lat.get(verb),
            "service": svc.get(verb),
            "requests": req.get(verb, {}).get("value", 0),
            "errors": err.get(verb, {}).get("value", 0),
        }
    return out


def _q_ms(hist_entry: Optional[dict], q: float) -> Optional[float]:
    if not hist_entry or not hist_entry.get("count"):
        return None
    v = obs_metrics.snapshot_quantile(hist_entry, q)
    return None if math.isnan(v) else round(v * 1e3, 3)


def build_report(
    spec: SLOSpec,
    workload: dict,
    recorder,
    fleet_before: dict,
    fleet_after: dict,
    fleet_samples: Sequence[Tuple[float, dict]] = (),
    timeline: Sequence[dict] = (),
    meta: Optional[dict] = None,
    attribution_window_s: float = DEFAULT_ATTRIBUTION_WINDOW_S,
) -> dict:
    """Assemble the ``SLOReport`` artifact.

    ``workload`` is a ``WorkloadEngine.run()`` summary; ``recorder`` any
    object with ``snapshot()`` plus ``error_samples``/``error_count``
    (duck-typed so tests can fake it); ``fleet_samples`` the periodic
    ``(wall_ts, fleet-merge)`` scrapes the windowed burn rates come from.
    """
    phases = workload.get("phases", [])
    timeline = list(timeline)
    client = _client_verb_series(recorder.snapshot())
    server = verb_windows(fleet_before, fleet_after)

    # windowed burn: per consecutive scrape pair, per server verb
    window_burns: List[dict] = []
    samples = list(fleet_samples)
    for (t_a, snap_a), (t_b, snap_b) in zip(samples, samples[1:]):
        for verb, w in verb_windows(snap_a, snap_b).items():
            obj = spec.for_verb(verb)
            target = obj.availability if obj else 0.999
            br = burn_rate(w["requests"], w["errors"], target)
            if br is not None:
                window_burns.append({"verb": verb, "t_start": t_a,
                                     "t_end": t_b, "requests":
                                     w["requests"], "errors": w["errors"],
                                     "burn_rate": round(br, 3)})

    scheduled_by_verb = workload.get("scheduled_by_verb", {})
    verbs: Dict[str, dict] = {}
    breaches: List[dict] = []
    for verb in sorted(client):
        c = client[verb]
        obj = spec.for_verb(verb)
        n, errs = c["requests"], c["errors"]
        availability = round((n - errs) / n, 6) if n else None
        p99_ms = _q_ms(c["latency"], 99)
        service_p99_ms = _q_ms(c["service"], 99)
        server_verb = CLIENT_TO_SERVER_VERB.get(verb, verb)
        srv = server.get(server_verb) if server_verb else None
        fleet_p99_ms = _q_ms(srv["hist"], 99) if srv else None
        ci = bucket_index(service_p99_ms / 1e3
                          if service_p99_ms is not None else None)
        fi = bucket_index(fleet_p99_ms / 1e3
                          if fleet_p99_ms is not None else None)
        bucket_delta = (abs(ci - fi)
                        if ci is not None and fi is not None else None)
        scheduled = scheduled_by_verb.get(verb, n)
        goodput = round((n - errs) / scheduled, 6) if scheduled else None
        overall_burn = burn_rate(
            n, errs, obj.availability if obj else 0.999)
        peak = max((w["burn_rate"] for w in window_burns
                    if w["verb"] == server_verb), default=None)
        entry = {
            "requests": n,
            "errors": errs,
            "availability": availability,
            "p99_ms": p99_ms,                      # from INTENDED send
            "p50_ms": _q_ms(c["latency"], 50),
            "service_p99_ms": service_p99_ms,      # from actual send
            "server_verb": server_verb,
            "fleet_requests": srv["requests"] if srv else None,
            "fleet_errors": srv["errors"] if srv else None,
            "fleet_p99_ms": fleet_p99_ms,
            "p99_bucket_delta": bucket_delta,
            "p99_bucket_agreement": (bucket_delta <= 1
                                     if bucket_delta is not None else None),
            "goodput": goodput,
            "burn_rate": (round(overall_burn, 3)
                          if overall_burn is not None else None),
            "burn_peak": peak,
            "objectives": {},
        }
        checks = []
        if obj is not None:
            if obj.availability is not None:
                checks.append(("availability", availability,
                               obj.availability,
                               availability is None
                               or availability >= obj.availability))
            if obj.p99_ms is not None:
                checks.append(("p99_ms", p99_ms, obj.p99_ms,
                               p99_ms is None or p99_ms <= obj.p99_ms))
            if obj.burn_rate_max is not None:
                measured = entry["burn_rate"]
                checks.append(("burn_rate", measured, obj.burn_rate_max,
                               measured is None
                               or measured <= obj.burn_rate_max))
            if obj.goodput_min is not None:
                checks.append(("goodput", goodput, obj.goodput_min,
                               goodput is None or goodput >= obj.goodput_min))
        verb_ok = True
        for name, measured, target, ok in checks:
            entry["objectives"][name] = {
                "target": target, "measured": measured, "ok": ok}
            if not ok:
                verb_ok = False
                # pick the moment that best explains the breach: the worst
                # burn window for rate/availability breaches, else the
                # run's midpoint (latency breaches are excursions whose
                # cause sits somewhere inside the run)
                worst = max((w for w in window_burns
                             if w["verb"] == server_verb),
                            key=lambda w: w["burn_rate"], default=None)
                at = (worst["t_end"] if worst and name in
                      ("availability", "burn_rate", "goodput")
                      else (workload.get("t_start", 0)
                            + workload.get("t_end", 0)) / 2)
                breaches.append({
                    "verb": verb, "objective": name,
                    "measured": measured, "target": target,
                    "attributed_to": _attribute_time(
                        at, timeline, phases, attribution_window_s),
                })
        entry["ok"] = verb_ok
        verbs[verb] = entry

    # per-error attribution
    attributed = 0
    error_samples_out = []
    for s in getattr(recorder, "error_samples", []):
        if ADMISSION_SHED_MARKER in str(s.get("error") or ""):
            # shed by admission control: the cause is the policy itself,
            # not any timeline event — an over-quota tenant being bounced
            # is the system WORKING, and must never read as unattributed
            cause = {"kind": "admission_shed"}
        else:
            cause = _attribute_time(s.get("ts", 0.0), timeline, phases,
                                    attribution_window_s)
        if cause is not None:
            attributed += 1
        error_samples_out.append(dict(s, attributed_to=cause))
    total_errors = getattr(recorder, "error_count", len(error_samples_out))
    sampled = len(error_samples_out)
    # errors beyond the sample cap inherit the sampled attribution ratio
    # conservatively: they count as unattributed unless every sample was
    # attributed
    if sampled and attributed == sampled:
        unattributed = 0
    elif sampled:
        unattributed = total_errors - attributed
    else:
        unattributed = total_errors

    report = {
        "schema": SCHEMA,
        "ts": time.time(),
        "meta": dict(meta or {}),
        "spec": spec.to_dict(),
        "workload": {k: v for k, v in workload.items() if k != "verbs"},
        "verbs": verbs,
        "window_burns": window_burns,
        "timeline": timeline,
        "breaches": breaches,
        "errors": {
            "total": total_errors,
            "sampled": sampled,
            "attributed": attributed,
            "unattributed": unattributed,
            "samples": error_samples_out,
        },
        "ok": (all(v["ok"] for v in verbs.values()) if verbs else False)
        and unattributed == 0,
    }
    return report


def human_summary(report: dict) -> str:
    """Operator-facing text rendering of an ``SLOReport``."""
    lines = []
    meta = report.get("meta", {})
    wl = report.get("workload", {})
    lines.append(
        f"SLO report — {'PASS' if report.get('ok') else 'FAIL'}"
        f" ({meta.get('mode', '?')} mode, shards={meta.get('shards')},"
        f" autoscale={meta.get('autoscale')}, kill={meta.get('kill')})")
    lines.append(
        f"  workload: {wl.get('completed')}/{wl.get('scheduled')} ops in "
        f"{wl.get('duration_s')}s ({wl.get('achieved_qps')} qps, "
        f"max sched lag {wl.get('max_sched_lag_s')}s)")
    header = (f"  {'verb':<7} {'reqs':>7} {'avail':>8} {'p99':>9} "
              f"{'fleet p99':>10} {'burn':>6} {'ok':>4}")
    lines.append(header)
    for verb, v in report.get("verbs", {}).items():
        avail = v.get("availability")
        p99 = v.get("p99_ms")
        fp99 = v.get("fleet_p99_ms")
        burn = v.get("burn_rate")
        lines.append(
            f"  {verb:<7} {v.get('requests', 0):>7} "
            f"{avail if avail is not None else '-':>8} "
            f"{(str(p99) + 'ms') if p99 is not None else '-':>9} "
            f"{(str(fp99) + 'ms') if fp99 is not None else '-':>10} "
            f"{burn if burn is not None else '-':>6} "
            f"{'yes' if v.get('ok') else 'NO':>4}")
    errs = report.get("errors", {})
    lines.append(f"  errors: {errs.get('total', 0)} total, "
                 f"{errs.get('attributed', 0)} attributed, "
                 f"{errs.get('unattributed', 0)} unattributed")
    for b in report.get("breaches", []):
        cause = b.get("attributed_to")
        cause_s = (f"{cause['kind']}"
                   + (f"/{cause.get('phase')}" if cause and
                      cause.get("phase") else "")
                   if cause else "UNATTRIBUTED")
        lines.append(
            f"  breach: {b['verb']}.{b['objective']} measured="
            f"{b['measured']} target={b['target']} -> {cause_s}")
    kills = sum(1 for e in report.get("timeline", [])
                if "kill" in e.get("kind", ""))
    cuts = sum(1 for e in report.get("timeline", [])
               if e.get("kind") == "elastic_cutover")
    lines.append(f"  timeline: {len(report.get('timeline', []))} events "
                 f"({kills} kills, {cuts} cutovers)")
    return "\n".join(lines)


def validate_report(report: dict) -> List[str]:
    """Schema check -> list of problems (empty = valid).  Used by the
    tier-1 smoke test and CI gating, so it validates structure, not
    pass/fail."""
    problems: List[str] = []
    if not isinstance(report, dict):
        return ["report is not a dict"]
    if report.get("schema") != SCHEMA:
        problems.append(f"schema != {SCHEMA!r}")
    for key in ("ts", "spec", "workload", "verbs", "timeline", "breaches",
                "errors", "ok"):
        if key not in report:
            problems.append(f"missing key {key!r}")
    verbs = report.get("verbs")
    if not isinstance(verbs, dict) or not verbs:
        problems.append("verbs empty or not a dict")
    else:
        for verb, v in verbs.items():
            for key in ("requests", "errors", "availability", "p99_ms",
                        "service_p99_ms", "fleet_p99_ms",
                        "p99_bucket_agreement", "burn_rate", "objectives",
                        "ok"):
                if key not in v:
                    problems.append(f"verbs[{verb!r}] missing {key!r}")
    errs = report.get("errors")
    if not isinstance(errs, dict):
        problems.append("errors not a dict")
    else:
        for key in ("total", "attributed", "unattributed", "samples"):
            if key not in errs:
                problems.append(f"errors missing {key!r}")
    for i, b in enumerate(report.get("breaches", [])):
        for key in ("verb", "objective", "measured", "target",
                    "attributed_to"):
            if key not in b:
                problems.append(f"breaches[{i}] missing {key!r}")
    return problems
