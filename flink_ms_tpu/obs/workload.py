"""Production-rehearsal workload engine — realistic open-loop traffic with
coordinated-omission-safe latency accounting (ROADMAP item 5; the spirit of
the reference's model-generator + partitioned load clients).

The pieces, composable on their own or through ``run_rehearsal``:

- ``ZipfKeys``         zipfian key popularity over a permuted id space, so
                       hot keys spread across shard owners instead of
                       clustering on one worker.
- ``VerbMix``          weighted blend over the serving verb surface
                       (GET/MGET/TOPK/TOPKV) plus ``UPDATE`` — SGD-style
                       factor writes through the journal.
- ``PhaseSchedule``    piecewise-constant rate plan: diurnal half-sine
                       ramps (``diurnal``) and warm/ramp/burst/cool plans
                       with a correlated burst (``ramp_burst``).
- ``OpenLoopPacer``    the pacing primitive: hands out *intended* send
                       times at a fixed rate and never skips a slot, so a
                       stalled server builds measurable backlog instead of
                       silently throttling the load (coordinated omission).
- ``WorkloadRecorder`` per-verb instruments on the shared
                       ``LATENCY_BUCKETS_S`` ladder: attributed latency
                       (done - *intended*; the SLO statistic) and service
                       latency (done - actual send) recorded side by side,
                       so client percentiles and fleet-scrape percentiles
                       are the same bucketed statistic.
- ``WorkloadEngine``   N paced worker threads draining a prefilled op
                       queue; phase transitions land in the obs event ring.
- ``run_rehearsal``    the closed loop: spawn an elastic sharded group,
                       drive the engine while the autoscaler and a chaos
                       kill act on the same fleet, scrape windows, and emit
                       an SLO report (``obs/slo.py``) attributing every
                       error and excursion to a timeline event.

CLI::

    python -m flink_ms_tpu.obs.workload --rehearsal [--out SLO_REPORT.json]
        [--shards 2 --replication 2 --durationS 12 --baseQps 120
         --burstQps 480 --autoscale live|dry|off --kill 1 --seed 0
         --abusiveQps 0    # >0: add an over-quota "abuse" tenant on top
         --subscribers 0   # >0: that many live push subscriptions ride
                           # the run (serve/push.py) and the SLO report
                           # gates update->push freshness
         --pushP99Ms 250]
    python -m flink_ms_tpu.obs.workload --group <topology-group> ...
        # attach mode: drive load + report against an ALREADY-RUNNING
        # elastic group instead of spawning one (no kill, no autoscaler)
"""

from __future__ import annotations

import bisect
import itertools
import json
import math
import os
import queue
import random
import signal
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import metrics as obs_metrics
from . import tracing as obs_tracing

__all__ = [
    "ZipfKeys", "VerbMix", "Phase", "PhaseSchedule", "OpenLoopPacer",
    "WorkloadRecorder", "ServingOps", "WorkloadEngine", "run_rehearsal",
    "main",
]

# instrument names — client twins of the server-side series, same ladder
CLIENT_LATENCY_HIST = "tpums_client_latency_seconds"     # done - intended
CLIENT_SERVICE_HIST = "tpums_client_service_seconds"     # done - sent
CLIENT_REQUESTS = "tpums_client_requests_total"
CLIENT_ERRORS = "tpums_client_errors_total"


class ZipfKeys:
    """Zipf(s) popularity over ``n`` keys with a seeded permutation of the
    id space: rank r (0-based) gets weight (r+1)^-s, but WHICH id holds
    rank r is shuffled, so the hot set is spread across shard owners the
    way real key hashes are — not clustered on worker 0."""

    def __init__(self, n: int, exponent: float = 1.1, seed: int = 0):
        if n <= 0:
            raise ValueError("need at least one key")
        self.n = n
        self.exponent = exponent
        ids = list(range(n))
        random.Random(seed).shuffle(ids)
        self.ids = ids                       # rank -> id
        weights = [(r + 1) ** -exponent for r in range(n)]
        self._cdf = list(itertools.accumulate(weights))
        self._total = self._cdf[-1]

    def sample(self, rng: random.Random) -> int:
        """One id drawn by popularity (rank 0 hottest)."""
        rank = bisect.bisect_left(self._cdf, rng.random() * self._total)
        return self.ids[min(rank, self.n - 1)]

    def hot_share(self, top_frac: float = 0.01) -> float:
        """Probability mass on the hottest ``top_frac`` of keys (skew
        diagnostic: uniform would give ``top_frac``)."""
        k = max(1, int(self.n * top_frac))
        return self._cdf[k - 1] / self._total


class VerbMix:
    """Weighted verb blend.  ``choose(rng)`` draws one verb; weights need
    not sum to anything in particular."""

    def __init__(self, weights: Dict[str, float]):
        items = [(v, w) for v, w in weights.items() if w > 0]
        if not items:
            raise ValueError("verb mix needs at least one positive weight")
        self.weights = dict(items)
        self._verbs = [v for v, _ in items]
        self._cum = list(itertools.accumulate(w for _, w in items))
        self._total = self._cum[-1]

    @classmethod
    def from_string(cls, spec: str) -> "VerbMix":
        """Parse ``"GET=60,MGET=15,TOPK=10,UPDATE=15"``."""
        weights: Dict[str, float] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            verb, _, w = part.partition("=")
            weights[verb.strip().upper()] = float(w) if w else 1.0
        return cls(weights)

    def choose(self, rng: random.Random) -> str:
        return self._verbs[
            bisect.bisect_left(self._cum, rng.random() * self._total)]

    def to_dict(self) -> Dict[str, float]:
        return dict(self.weights)


@dataclass(frozen=True)
class Phase:
    """One piecewise-constant segment of the rate plan."""
    name: str
    duration_s: float
    rate_qps: float


class PhaseSchedule:
    """A sequence of ``Phase`` segments; the engine derives one intended
    send time per scheduled request from it (open loop: the plan never
    reacts to server speed)."""

    def __init__(self, phases: Sequence[Phase]):
        self.phases = list(phases)
        if not self.phases:
            raise ValueError("schedule needs at least one phase")

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def rate_at(self, t: float) -> float:
        off = 0.0
        for p in self.phases:
            if t < off + p.duration_s:
                return p.rate_qps
            off += p.duration_s
        return 0.0

    def phase_at(self, t: float) -> Optional[Phase]:
        off = 0.0
        for p in self.phases:
            if t < off + p.duration_s:
                return p
            off += p.duration_s
        return None

    def windows(self) -> List[Tuple[float, float, Phase]]:
        """[(start_offset, end_offset, phase), ...]"""
        out, off = [], 0.0
        for p in self.phases:
            out.append((off, off + p.duration_s, p))
            off += p.duration_s
        return out

    def intended_offsets(self) -> List[Tuple[float, str]]:
        """Every scheduled send as (offset_s, phase_name), evenly paced
        within each phase at 1/rate.  This is the open-loop contract: the
        list is fixed up front and every slot is sent (or recorded late),
        never skipped."""
        out: List[Tuple[float, str]] = []
        off = 0.0
        for p in self.phases:
            if p.rate_qps > 0:
                n = int(p.duration_s * p.rate_qps)
                step = 1.0 / p.rate_qps
                out.extend((off + i * step, p.name) for i in range(n))
            off += p.duration_s
        return out

    @classmethod
    def diurnal(cls, base_qps: float, peak_qps: float, duration_s: float,
                steps: int = 8) -> "PhaseSchedule":
        """Half-sine day: base -> peak -> base over ``duration_s`` in
        ``steps`` constant-rate segments."""
        steps = max(2, steps)
        seg = duration_s / steps
        phases = []
        for i in range(steps):
            frac = math.sin(math.pi * (i + 0.5) / steps)
            rate = base_qps + (peak_qps - base_qps) * frac
            phases.append(Phase(f"diurnal{i}", seg, rate))
        return cls(phases)

    @classmethod
    def ramp_burst(cls, base_qps: float, peak_qps: float, burst_qps: float,
                   warm_s: float, ramp_s: float, burst_s: float,
                   cool_s: float, ramp_steps: int = 3) -> "PhaseSchedule":
        """Warm at base, ramp linearly to peak, hold a correlated burst
        (every client surging together), cool back to base.  The burst
        phase name contains ``burst`` — the SLO attribution layer treats
        it as a first-class excursion cause."""
        phases = [Phase("warm", warm_s, base_qps)]
        ramp_steps = max(1, ramp_steps)
        for i in range(ramp_steps):
            rate = base_qps + (peak_qps - base_qps) * (i + 1) / ramp_steps
            phases.append(Phase(f"ramp{i}", ramp_s / ramp_steps, rate))
        phases.append(Phase("burst", burst_s, burst_qps))
        phases.append(Phase("cool", cool_s, base_qps))
        return cls(phases)


class OpenLoopPacer:
    """Fixed-rate slot dispenser for open-loop load: ``next_slot()``
    returns the *intended* send time (``time.perf_counter`` domain),
    sleeping only when ahead of schedule.  When the caller falls behind
    (a stalled server), slots return immediately with past timestamps —
    the backlog is real and the latency recorded from the intended time
    carries it, which is exactly the coordinated-omission fix."""

    def __init__(self, rate_qps: float, t0: Optional[float] = None):
        if rate_qps <= 0:
            raise ValueError("rate must be positive")
        self.interval_s = 1.0 / rate_qps
        self.t_next = time.perf_counter() if t0 is None else t0

    def next_slot(self) -> float:
        t = self.t_next
        self.t_next = t + self.interval_s
        now = time.perf_counter()
        if t > now:
            time.sleep(t - now)
        return t

    @property
    def lag_s(self) -> float:
        """How far behind schedule the caller currently is."""
        return max(0.0, time.perf_counter() - self.t_next)


class WorkloadRecorder:
    """Per-verb client-side instruments on the shared latency ladder.

    Two histograms per verb, same buckets as the server's
    ``tpums_server_latency_seconds``:

    - ``tpums_client_latency_seconds{verb=}``  done - INTENDED send
      (coordinated-omission-safe; the SLO statistic)
    - ``tpums_client_service_seconds{verb=}``  done - actual send
      (comparable to the fleet-scraped server percentile)

    plus request/error counters and a bounded ring of timestamped error
    samples for event attribution.  Defaults to a PRIVATE registry so a
    rehearsal doesn't pollute the process-global one the fleet scrape of
    an in-process worker would see."""

    def __init__(self, registry: Optional[obs_metrics.MetricsRegistry] = None,
                 max_error_samples: int = 512):
        self.registry = registry or obs_metrics.MetricsRegistry()
        self.max_error_samples = max_error_samples
        self.error_samples: List[dict] = []
        self.error_count = 0
        self._lock = threading.Lock()
        self._instruments: Dict[str, tuple] = {}

    def _for_verb(self, verb: str) -> tuple:
        inst = self._instruments.get(verb)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(verb)
                if inst is None:
                    inst = (
                        self.registry.histogram(CLIENT_LATENCY_HIST,
                                                verb=verb),
                        self.registry.histogram(CLIENT_SERVICE_HIST,
                                                verb=verb),
                        self.registry.counter(CLIENT_REQUESTS, verb=verb),
                        self.registry.counter(CLIENT_ERRORS, verb=verb),
                    )
                    self._instruments[verb] = inst
        return inst

    def record(self, verb: str, intended_t: float, sent_t: float,
               done_t: float, ok: bool, error: Optional[str] = None,
               phase: Optional[str] = None,
               wall_ts: Optional[float] = None) -> None:
        lat_h, svc_h, req_c, err_c = self._for_verb(verb)
        lat_h.observe(max(done_t - intended_t, 0.0))
        svc_h.observe(max(done_t - sent_t, 0.0))
        req_c.inc()
        if not ok:
            err_c.inc()
            with self._lock:
                self.error_count += 1
                if len(self.error_samples) < self.max_error_samples:
                    self.error_samples.append({
                        "ts": time.time() if wall_ts is None else wall_ts,
                        "verb": verb,
                        "phase": phase,
                        "error": error,
                        "latency_s": round(max(done_t - intended_t, 0.0), 6),
                    })

    def snapshot(self) -> dict:
        return self.registry.snapshot()

    def verb_stats(self) -> Dict[str, dict]:
        """Per-verb summary off the live instruments: counts, availability,
        and bucketed p50/p99 for both the attributed and service series."""
        out: Dict[str, dict] = {}
        for verb, (lat_h, svc_h, req_c, err_c) in sorted(
                self._instruments.items()):
            n, errs = req_c.value, err_c.value
            stats = {
                "requests": n,
                "errors": errs,
                "availability": round((n - errs) / n, 6) if n else None,
            }
            for prefix, h in (("", lat_h), ("service_", svc_h)):
                for q in (50, 99):
                    v = h.quantile(q)
                    stats[f"{prefix}p{q}_ms"] = (
                        None if math.isnan(v) else round(v * 1e3, 3))
            out[verb] = stats
        return out


class ServingOps:
    """Executes workload verbs against a sharded serving group.

    ``client_factory`` builds one client per worker thread (the elastic/HA
    clients are single-threaded by contract).  ``UPDATE`` is an SGD-style
    factor write: a fresh factor row for a popular user appended to the
    journal every consumer tails — the write half of the paper's
    train->serve->update loop, paced inside the same blend as the reads.

    ``execute`` returns False for a semantic miss (every seeded key must
    resolve) and raises on transport errors; both count as request errors.

    Verbs may carry a tenant tag — ``"GET~abuse"`` — resolved through
    ``client_factories[tag]`` to a per-tag (per-tenant) client, so one
    engine drives a multi-tenant blend and the recorder's per-verb stats
    split by tenant for free.
    """

    VERBS = ("GET", "MGET", "TOPK", "TOPKV", "UPDATE")

    def __init__(self, client_factory: Callable[[], object], keys: ZipfKeys,
                 state: str, journal=None, dim: int = 4,
                 mget_size: int = 4, topk_k: int = 8, topkv_users: int = 2,
                 update_plane=None,
                 client_factories: Optional[Dict[str, Callable]] = None):
        self.client_factory = client_factory
        # tag -> factory for tenant-tagged verbs; "" is the untagged default
        self.client_factories = dict(client_factories or {})
        self.client_factories.setdefault("", client_factory)
        self.keys = keys
        self.state = state
        self.journal = journal
        self.dim = dim
        self.mget_size = mget_size
        self.topk_k = topk_k
        self.topkv_users = topkv_users
        # serve/update_plane.UpdatePlaneClient: when set, UPDATE submits a
        # real rating into the sharded update plane (the co-located SGD
        # workers do the factor math) instead of appending a synthetic
        # factor row straight to the journal
        self.update_plane = update_plane
        self._tl = threading.local()
        self._journal_lock = threading.Lock()

    def _client(self, tag: str = ""):
        clients = getattr(self._tl, "clients", None)
        if clients is None:
            clients = self._tl.clients = {}
        c = clients.get(tag)
        if c is None:
            factory = self.client_factories.get(tag)
            if factory is None:
                raise ValueError(f"no client factory for verb tag {tag!r}")
            c = clients[tag] = factory()
        return c

    def execute(self, verb: str, rng: random.Random) -> bool:
        verb, _, tag = verb.partition("~")
        c = self._client(tag)
        if verb == "GET":
            return c.query_state(
                self.state, f"{self.keys.sample(rng)}-U") is not None
        if verb == "MGET":
            ks = [f"{self.keys.sample(rng)}-U"
                  for _ in range(self.mget_size)]
            return all(v is not None
                       for v in c.query_states(self.state, ks))
        if verb == "TOPK":
            return c.topk(self.state, str(self.keys.sample(rng)),
                          self.topk_k) is not None
        if verb == "TOPKV":
            users = [str(self.keys.sample(rng))
                     for _ in range(self.topkv_users)]
            return all(r is not None for r in
                       c.topk_many(self.state, users, self.topk_k))
        if verb == "UPDATE":
            if self.update_plane is not None:
                # the closed loop for real: a rating routed through the
                # sharded update plane — co-located SGD does the math and
                # publishes the resulting factor rows
                uid = self.keys.sample(rng)
                iid = self.keys.sample(rng)
                self.update_plane.submit(uid, iid, rng.uniform(0.5, 5.0))
                return True
            if self.journal is None:
                raise RuntimeError("UPDATE verb needs a journal or an "
                                   "update plane")
            from ..core import formats as F
            uid = self.keys.sample(rng)
            row = F.format_als_row(
                uid, "U", [rng.gauss(0.0, 1.0) for _ in range(self.dim)])
            with self._journal_lock:
                self.journal.append([row])
            return True
        raise ValueError(f"unknown verb {verb!r}")

    def close_local(self) -> None:
        """Close THIS thread's clients (each engine worker calls it on the
        way out)."""
        clients = getattr(self._tl, "clients", None)
        if clients:
            self._tl.clients = {}
            for c in clients.values():
                try:
                    c.close()
                except Exception:
                    pass


class WorkloadEngine:
    """Open-loop driver: the full op list (intended time, verb, phase) is
    materialized from the schedule up front, then ``threads`` workers
    drain it in order, sleeping only when AHEAD of an op's intended time.
    A slow server never slows the schedule down — late ops execute
    immediately and their latency, measured from the intended time,
    carries the queueing delay.  Phase starts are announced on the obs
    event ring (``workload_phase``) so the SLO layer can attribute
    excursions to bursts."""

    def __init__(self, ops, schedule: PhaseSchedule, mix: VerbMix,
                 recorder: Optional[WorkloadRecorder] = None,
                 threads: int = 4, seed: int = 0, name: str = "workload"):
        self.ops = ops
        self.schedule = schedule
        self.mix = mix
        self.recorder = recorder or WorkloadRecorder()
        self.threads = max(1, threads)
        self.seed = seed
        self.name = name
        self.stop_flag = threading.Event()

    def _build_plan(self) -> List[Tuple[float, str, str]]:
        rng = random.Random(self.seed)
        return [(off, self.mix.choose(rng), phase)
                for off, phase in self.schedule.intended_offsets()]

    def run(self) -> dict:
        plan = self._build_plan()
        scheduled_by_verb: Dict[str, int] = {}
        for _, verb, _ in plan:
            scheduled_by_verb[verb] = scheduled_by_verb.get(verb, 0) + 1
        q: "queue.SimpleQueue" = queue.SimpleQueue()
        for item in plan:
            q.put(item)
        # small lead so workers spawned below don't start behind schedule
        t0 = time.perf_counter() + 0.05
        wall0 = time.time() + 0.05
        max_lag = [0.0] * self.threads
        completed = [0] * self.threads
        ok_count = [0] * self.threads

        def worker(widx: int) -> None:
            rng = random.Random((self.seed << 8) + widx)
            try:
                while not self.stop_flag.is_set():
                    try:
                        off, verb, phase = q.get_nowait()
                    except queue.Empty:
                        break
                    intended = t0 + off
                    now = time.perf_counter()
                    if intended > now:
                        time.sleep(intended - now)
                    else:
                        max_lag[widx] = max(max_lag[widx], now - intended)
                    sent = time.perf_counter()
                    ok, err = True, None
                    try:
                        ok = bool(self.ops.execute(verb, rng))
                        if not ok:
                            err = "miss"
                    except Exception as e:
                        ok, err = False, repr(e)
                    done = time.perf_counter()
                    completed[widx] += 1
                    ok_count[widx] += 1 if ok else 0
                    self.recorder.record(
                        verb, intended, sent, done, ok, error=err,
                        phase=phase, wall_ts=wall0 + (done - t0))
            finally:
                close = getattr(self.ops, "close_local", None)
                if close is not None:
                    close()

        workers = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(self.threads)]
        for w in workers:
            w.start()
        # announce phases at their PLANNED times (the plan is open-loop, so
        # the wall-clock phase windows are known up front)
        phase_windows = []
        for start, end, p in self.schedule.windows():
            target = t0 + start
            while not self.stop_flag.is_set():
                now = time.perf_counter()
                if now >= target:
                    break
                time.sleep(min(0.1, target - now))
            if self.stop_flag.is_set():
                break
            obs_tracing.event("workload_phase", workload=self.name,
                              phase=p.name, rate_qps=p.rate_qps,
                              duration_s=p.duration_s)
            phase_windows.append({
                "name": p.name, "rate_qps": p.rate_qps,
                "t_start": wall0 + start, "t_end": wall0 + end,
            })
        for w in workers:
            w.join()
        dur = time.perf_counter() - t0
        total, n_ok = sum(completed), sum(ok_count)
        return {
            "name": self.name,
            "scheduled": len(plan),
            "scheduled_by_verb": scheduled_by_verb,
            "completed": total,
            "ok": n_ok,
            "errors": total - n_ok,
            "goodput": round(n_ok / len(plan), 6) if plan else None,
            "duration_s": round(dur, 3),
            "planned_duration_s": round(self.schedule.duration_s, 3),
            "achieved_qps": round(total / dur, 1) if dur > 0 else None,
            "max_sched_lag_s": round(max(max_lag), 3) if max_lag else 0.0,
            "threads": self.threads,
            "mix": self.mix.to_dict(),
            "phases": phase_windows,
            "t_start": wall0,
            "t_end": wall0 + dur,
            "verbs": self.recorder.verb_stats(),
        }

    def stop(self) -> None:
        self.stop_flag.set()


# ---------------------------------------------------------------------------
# closed-loop rehearsal
# ---------------------------------------------------------------------------

DEFAULT_VERB_WEIGHTS = {
    "GET": 55.0, "MGET": 15.0, "TOPK": 8.0, "TOPKV": 4.0, "UPDATE": 18.0,
}

# event kinds the rehearsal timeline keeps (everything the SLO layer can
# attribute an excursion to, plus the phases themselves)
_TIMELINE_KINDS = (
    "workload_phase", "rehearsal_kill", "chaos_kill", "chaos_kill_warming",
    "chaos_teardown",
    "elastic_scale_start", "elastic_cutover", "elastic_drained",
    "elastic_scale_abort", "generation_swap", "failover",
    "replica_respawn", "autoscale_decision",
    "rollout_scale_start", "rollout_cutover", "rollout_drained",
    "rollout_scale_abort", "rollout_verified", "rollout_rollback",
    "edge_hedge", "edge_shed", "proxy_reconnect",
)

# query verbs an abusive tenant replays (UPDATE rides the journal/update
# plane, not the admission-controlled query path)
_ABUSE_VERBS = ("GET", "MGET", "TOPK", "TOPKV")
ABUSIVE_TENANT = "abuse"


def _run_subscriber(idx: int, live_group: str, edge: int, state: str,
                    key: str, stop: threading.Event, stats: dict,
                    lock: threading.Lock) -> None:
    """One push subscriber (serve/push.py): hold a ``su=1`` connection
    with a KEY subscription on a hot factor row, drain deltas until
    told to stop.  A dead connection (replica kill, reshard cutover,
    proxy death) reconnects and RESUMEs at the last delivered seq — the
    replay-or-snapshot answer is counted either way, so the stats show
    churn without ever double-counting a delta."""
    from ..serve import registry as reg_mod
    from ..serve.client import QueryClient
    from ..serve.elastic import generation_group
    from ..serve.ha import resolve_shard_endpoints
    from ..serve.sharded import owner_of

    qgroup = reg_mod.qualify_group(live_group)

    def connect():
        if edge > 0:
            from ..serve.edge import EdgeClient
            return EdgeClient(live_group, proto="b2", push=True,
                              timeout_s=10.0)
        topo = reg_mod.resolve_topology(qgroup)
        if topo is None:
            raise ConnectionError(f"no topology for {live_group!r}")
        gen, shards = int(topo["gen"]), int(topo["shards"])
        eps = resolve_shard_endpoints(generation_group(qgroup, gen),
                                      owner_of(key, shards))
        if not eps:
            raise ConnectionError(f"no endpoints for key {key!r}")
        host, port = eps[idx % len(eps)]
        return QueryClient(host=host, port=port, proto="b2", push=True,
                           timeout_s=10.0)

    c = None
    sub = None
    backoff = 0
    while not stop.is_set():
        try:
            if c is None:
                c = connect()
                if sub is None:
                    got = c.subscribe_key(state, key)
                else:
                    got = c.resume_subscription(
                        state, "KEY", key, 0, sub["sub_id"], sub["seq"])
                    with lock:
                        stats["resumes"] += 1
                sub = {"sub_id": got["sub_id"], "seq": got["seq"]}
                backoff = 0
            p = c.next_push(timeout_s=0.25)
            if p is not None:
                sub["seq"] = p[1]
                with lock:
                    stats["pushes"] += 1
        except Exception:
            with lock:
                stats["errors"] += 1
            try:
                if c is not None:
                    c.close()
            except Exception:
                pass
            c = None
            backoff = min(backoff + 1, 10)
            stop.wait(0.05 * backoff)
    try:
        if c is not None:
            c.close()
    except Exception:
        pass


def _seed_journal(base: str, topic: str, users: int, dim: int, seed: int):
    from ..core import formats as F
    from ..serve.journal import Journal

    journal = Journal(os.path.join(base, "bus"), topic)
    rng = random.Random(seed)
    rows = [F.format_als_row(u, "U",
                             [rng.gauss(0.0, 1.0) for _ in range(dim)])
            for u in range(users)]
    rows += [F.format_als_row(i, "I",
                              [rng.gauss(0.0, 1.0) for _ in range(dim)])
             for i in range(users)]
    journal.append(rows)
    return journal


def run_rehearsal(
    out_path: Optional[str] = None,
    shards: int = 2,
    replication: int = 2,
    users: int = 400,
    dim: int = 4,
    base_qps: float = 120.0,
    peak_qps: float = 240.0,
    burst_qps: float = 480.0,
    warm_s: float = 2.0,
    ramp_s: float = 3.0,
    burst_s: float = 4.0,
    cool_s: float = 2.0,
    threads: int = 4,
    seed: int = 0,
    verb_weights: Optional[Dict[str, float]] = None,
    autoscale: str = "off",          # off | dry | live
    kill: bool = False,
    kill_at_s: Optional[float] = None,
    scrape_interval_s: float = 1.0,
    spec=None,
    group: str = "rehearsal",
    attach_group: Optional[str] = None,
    zipf_exponent: float = 1.1,
    update_plane: bool = True,
    abusive_qps: float = 0.0,
    watch: bool = False,
    watch_rules=None,
    watch_canary=None,
    watch_interval_s: float = 0.5,
    edge: int = 0,
    subscribers: int = 0,
    push_p99_ms: float = 250.0,
) -> dict:
    """The closed loop: elastic sharded group + open-loop zipfian mixed-verb
    engine + autoscaler + one chaos kill, all acting on the same fleet,
    reported as an SLO artifact (``obs/slo.py``) with every error and
    excursion attributed to a timeline event.

    With ``attach_group`` set, drives load against an already-running
    elastic group instead (no spawn, no kill, no autoscaler) — the
    operator-facing smoke mode.

    With ``abusive_qps > 0`` the blend becomes two-tenant: a second,
    ``~abuse``-tagged replay of the query verbs is layered ON TOP of the
    in-quota schedule (in-quota offered rates are unchanged) and the
    ``abuse`` tenant's admission quota is set to HALF its base offered
    rate (``TPUMS_ADMIT_TENANT_QPS``), so it runs persistently over quota
    while the untagged tenant stays unlimited.  Abusive verbs carry
    objective-free SLO entries — their sheds are attributed
    (``admission_shed``), not breached — and the report's gate becomes
    "in-quota traffic unharmed while the abuser is shed".

    With ``watch=True`` a live ``obs.watch.FleetWatcher`` runs through the
    load window (its own cadence, ``watch_interval_s``; rules default to
    the fleet baseline or ``watch_rules``; an optional ``watch_canary``
    probes live model quality) and the report gains an ``"alerts"``
    section — the live incident timeline with per-kill detection latency
    and attribution, instead of only the terminal SLO post-mortem.

    With ``edge > 0`` that many edge proxies (``serve/edge.py``) are
    spawned in front of the fleet and EVERY client thread becomes an
    ``EdgeClient`` — the full verb mix runs through the proxy tier
    (multiplexing, coalescing, hedging, edge admission), and the SLO
    attribution must still come out clean: ``edge_hedge``/``edge_shed``/
    ``proxy_reconnect`` are timeline events, never unattributed errors.
    In attach mode the proxies must already be registered for the group.

    With ``subscribers > 0`` that many live push subscriptions
    (``serve/push.py``: KEY subs on the zipf-hot factor rows, through
    the edge tier when ``edge > 0``) ride the whole run, draining
    deltas fed by the UPDATE verb's factor writes.  The report gains a
    ``"push"`` section — subscriber population, deltas delivered,
    resume churn, and the fleet's update→push p99 off
    ``tpums_push_latency_seconds`` — and the overall gate additionally
    requires that p99 under ``push_p99_ms`` with at least one delta
    delivered: push freshness becomes an SLO, not a hope.
    """
    from . import slo as obs_slo
    from .scrape import scrape_fleet
    from ..serve.client import RetryPolicy
    from ..serve.consumer import ALS_STATE

    if autoscale not in ("off", "dry", "live"):
        raise ValueError("autoscale must be off|dry|live")

    weights = dict(verb_weights or DEFAULT_VERB_WEIGHTS)
    if abusive_qps > 0:
        q_weights = {v: w for v, w in weights.items() if v in _ABUSE_VERBS}
        if not q_weights:
            raise ValueError("abusive tenant needs at least one query verb "
                             "in the mix")
        # layer the abusive replay on top: schedule rates grow by
        # (1 + abusive/base) and the tagged share is sized so the UNTAGGED
        # offered rates match the caller's base/peak/burst exactly while
        # the abuser offers abusive_qps at base (scaling with the plan)
        k = abusive_qps / base_qps
        scale = k * sum(weights.values()) / sum(q_weights.values())
        for v, w in q_weights.items():
            weights[f"{v}~{ABUSIVE_TENANT}"] = w * scale
        base_qps, peak_qps, burst_qps = (
            base_qps * (1 + k), peak_qps * (1 + k), burst_qps * (1 + k))
    mix = VerbMix(weights)
    schedule = PhaseSchedule.ramp_burst(
        base_qps, peak_qps, burst_qps, warm_s, ramp_s, burst_s, cool_s)
    if spec is None:
        spec = obs_slo.SLOSpec(
            list(obs_slo.SLOSpec.default(
                sorted(v for v in mix.weights if "~" not in v)).objectives)
            + [obs_slo.SLOObjective(verb=v, availability=None, p99_ms=None,
                                    burn_rate_max=None, goodput_min=None)
               for v in sorted(mix.weights) if "~" in v])

    saved_env = {k: os.environ.get(k) for k in
                 ("TPUMS_REGISTRY_DIR", "TPUMS_HEARTBEAT_S",
                  "TPUMS_REPLICA_TTL_S", "TPUMS_ADMIT_TENANT_QPS")}
    base = tempfile.mkdtemp(prefix="tpums_rehearsal_")
    ctl = None
    autoscaler = None
    watcher = None
    edge_procs: list = []
    sampler_stop = threading.Event()
    scrapes: List[Tuple[float, dict]] = []

    def sampler() -> None:
        while not sampler_stop.wait(scrape_interval_s):
            try:
                snap = scrape_fleet()
                scrapes.append((time.time(), snap["fleet"]))
            except Exception:
                pass

    try:
        if attach_group is None:
            # fast liveness for a short rehearsal (operator values win)
            if saved_env["TPUMS_HEARTBEAT_S"] is None:
                os.environ["TPUMS_HEARTBEAT_S"] = "0.25"
            if saved_env["TPUMS_REPLICA_TTL_S"] is None:
                os.environ["TPUMS_REPLICA_TTL_S"] = "1.5"
            if saved_env["TPUMS_REGISTRY_DIR"] is None:
                os.environ["TPUMS_REGISTRY_DIR"] = os.path.join(
                    base, "registry")
            if abusive_qps > 0:
                # quota = half the abuser's base offered rate: persistently
                # 2x over quota, so the shedder works for the whole run
                os.environ["TPUMS_ADMIT_TENANT_QPS"] = (
                    f"{ABUSIVE_TENANT}={abusive_qps / 2:g}")
            from ..serve.elastic import (Autoscaler, AutoscalerPolicy,
                                         ScaleController)

            journal = _seed_journal(base, "models", users, dim, seed)
            # real sharded updates: the workers co-host the update plane
            # (serve/update_plane.py) and the UPDATE verb submits ratings
            # into it instead of appending synthetic factor rows
            extra_args = (["--updatePlane", "true",
                           "--pollInterval", "0.02"]
                          if update_plane else [])
            ctl = ScaleController(group, journal.dir, "models",
                                  port_dir=os.path.join(base, "ports"),
                                  ready_timeout_s=180,
                                  extra_args=extra_args)
            ctl.scale_to(shards, replicas=replication)
            live_group = group
            if edge > 0:
                from ..serve.edge import spawn_edge_procs
                edge_procs, _ = spawn_edge_procs(
                    live_group, edge, os.path.join(base, "edge_ports"))
            if autoscale != "off":
                # trip on the burst, not the ramp: threshold above the
                # per-shard peak rate but below the per-shard burst rate
                policy = AutoscalerPolicy(
                    qps_high_per_shard=(peak_qps / shards) * 1.3,
                    qps_low_per_shard=0.0,       # no scale-in mid-rehearsal
                    p99_high_s=10.0,             # qps-driven, deterministic
                    min_shards=shards,
                    max_shards=shards * 2,
                    cooldown_s=max(burst_s, 5.0),
                )
                autoscaler = Autoscaler(ctl, policy, interval_s=1.0,
                                        dry_run=(autoscale == "dry"))
                autoscaler.start()
        else:
            journal = None
            live_group = attach_group
            kill = False
            autoscale = "off"

        if edge > 0:
            # every worker thread talks to the proxy tier: one thin
            # connection, no shard/generation knowledge client-side
            def client_factory():
                from ..serve.edge import EdgeClient
                return EdgeClient(
                    live_group, timeout_s=10.0,
                    retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                      max_backoff_s=0.5))
        else:
            def client_factory():
                from ..serve.elastic import ElasticClient
                return ElasticClient(
                    live_group, timeout_s=10.0,
                    retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                      max_backoff_s=0.5))

        client_factories = None
        if abusive_qps > 0:
            def abusive_factory():
                # tenant= rides the wire (tab: trailing tn= field; B2:
                # HELLO-bound); sheds come back as "E\tover quota"
                # RuntimeErrors, which the HA client does NOT failover on
                if edge > 0:
                    from ..serve.edge import EdgeClient
                    return EdgeClient(
                        live_group, timeout_s=10.0,
                        retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                          max_backoff_s=0.5),
                        tenant=ABUSIVE_TENANT)
                from ..serve.elastic import ElasticClient
                return ElasticClient(
                    live_group, timeout_s=10.0,
                    retry=RetryPolicy(attempts=6, backoff_s=0.02,
                                      max_backoff_s=0.5),
                    tenant=ABUSIVE_TENANT)
            client_factories = {ABUSIVE_TENANT: abusive_factory}

        upd_client = None
        if update_plane and journal is not None:
            from ..serve.update_plane import UpdatePlaneClient
            upd_client = UpdatePlaneClient(journal.dir, "models")
        zkeys = ZipfKeys(users, zipf_exponent, seed)
        ops = ServingOps(client_factory, zkeys,
                         ALS_STATE, journal=journal, dim=dim,
                         update_plane=upd_client,
                         client_factories=client_factories)
        recorder = WorkloadRecorder()
        engine = WorkloadEngine(ops, schedule, mix, recorder=recorder,
                                threads=threads, seed=seed,
                                name="rehearsal")

        # warm the serving path before the clock starts: the first TOPK
        # per worker JIT-compiles its scoring program (~1s) — inside the
        # open loop that stall would masquerade as a schedule-wide
        # latency excursion no timeline event explains
        warm_rng = random.Random(seed + 1)
        for verb in ("GET", "MGET", "TOPK", "TOPKV"):
            if verb in mix.weights:
                for _ in range(2):
                    try:
                        ops.execute(verb, warm_rng)
                    except Exception:
                        break
        ops.close_local()

        # push subscriber population: live subscriptions on the hottest
        # factor rows, fed by the UPDATE verb's writes for the whole run
        push_stop = threading.Event()
        push_stats = {"pushes": 0, "resumes": 0, "errors": 0}
        push_lock = threading.Lock()
        sub_threads: List[threading.Thread] = []
        if subscribers > 0:
            hot_n = max(1, min(16, users))
            for i in range(subscribers):
                key = f"{zkeys.ids[i % hot_n]}-U"
                t = threading.Thread(
                    target=_run_subscriber,
                    args=(i, live_group, edge, ALS_STATE, key, push_stop,
                          push_stats, push_lock),
                    daemon=True, name=f"tpums-sub-{i}")
                t.start()
                sub_threads.append(t)

        # the SLO timeline starts HERE: the bring-up cutover above is
        # plumbing, not an excursion cause
        t_run_start = time.time()
        # first scrape before load, then a sampling thread through the run
        fleet_before = scrape_fleet()["fleet"]
        scrapes.append((time.time(), fleet_before))
        sampler_t = threading.Thread(target=sampler, daemon=True)
        sampler_t.start()

        if watch:
            from .watch import FleetWatcher
            watcher = FleetWatcher(interval_s=watch_interval_s,
                                   rules=watch_rules,
                                   canary=watch_canary,
                                   scope=live_group).start()

        killer_t = None
        if kill and ctl is not None:
            if kill_at_s is None:
                kill_at_s = warm_s + ramp_s / 2.0
            t_kill = time.time() + kill_at_s

            def killer() -> None:
                while time.time() < t_kill and not sampler_stop.is_set():
                    time.sleep(0.05)
                sup = ctl.active_supervisor
                if sup is None:
                    return
                # last replica of shard 0: with R>=2 failover keeps the
                # shard serving; with R=1 this is a real outage the report
                # must attribute
                victim = (0, replication - 1)
                proc = sup.procs.get(victim)
                if proc is not None and proc.poll() is None:
                    obs_tracing.event("rehearsal_kill", shard=victim[0],
                                      replica=victim[1], pid=proc.pid,
                                      group=sup.group_of(victim[0]))
                    proc.send_signal(signal.SIGKILL)

            killer_t = threading.Thread(target=killer, daemon=True)
            killer_t.start()

        summary = engine.run()

        if killer_t is not None:
            killer_t.join(timeout=10)
        if autoscaler is not None:
            autoscaler.stop()
        # give in-flight deltas a beat to land before stopping the drain
        if sub_threads:
            time.sleep(0.5)
            push_stop.set()
            for t in sub_threads:
                t.join(timeout=10)
        sampler_stop.set()
        sampler_t.join(timeout=10)
        alerts_section = None
        if watcher is not None:
            # one last synchronous tick so a kill in the final moments is
            # still observed before the loop stops
            try:
                watcher.tick()
            except Exception:
                pass
            watcher.stop()
            alerts_section = watcher.watch_summary()
            alerts_section["transitions"] = list(watcher.engine.history)
        fleet_after = scrape_fleet()["fleet"]
        scrapes.append((time.time(), fleet_after))

        # the autoscaler announces its own acted-on decisions via
        # events_counter("autoscale_decision"), so the ring has everything
        timeline = sorted(
            (e for e in obs_tracing.recent_events()
             if e.get("ts", 0) >= t_run_start
             and e.get("kind") in _TIMELINE_KINDS),
            key=lambda e: e.get("ts", 0))

        report = obs_slo.build_report(
            spec=spec,
            workload=summary,
            recorder=recorder,
            fleet_before=fleet_before,
            fleet_after=fleet_after,
            fleet_samples=scrapes,
            timeline=timeline,
            meta={
                "mode": "attach" if attach_group else "spawn",
                "group": live_group,
                "shards": shards,
                "replication": replication,
                "autoscale": autoscale,
                "kill": bool(kill),
                "users": users,
                "zipf_exponent": zipf_exponent,
                "seed": seed,
                "abusive_qps": abusive_qps,
                "edge": edge,
                "subscribers": subscribers,
            },
        )
        if alerts_section is not None:
            report["alerts"] = alerts_section
        if subscribers > 0:
            # push freshness as an SLO: the fleet's own update→push
            # ladder (tpums_push_latency_seconds) must hold its p99
            # under budget AND at least one delta must have actually
            # reached a subscriber — a silent push plane with a vacuous
            # histogram does not pass.  Folded over the sampler's scrape
            # SERIES, not the endpoint pair: an autoscaler cutover or a
            # chaos kill mid-run replaces the worker processes whose
            # counters held the window, and the endpoint difference
            # would read a healthy plane as a silent one (push_freshness
            # is reset-aware pair by pair).
            from .scrape import fleet_signals, push_freshness
            sig = fleet_signals(fleet_before, fleet_after)
            fresh = push_freshness(scrapes)
            p99_s = fresh["p99_s"]
            with push_lock:
                delivered = push_stats["pushes"]
                resumes = push_stats["resumes"]
                sub_errors = push_stats["errors"]
            fresh_ok = bool(delivered > 0 and p99_s is not None
                            and p99_s * 1e3 <= push_p99_ms)
            report["push"] = {
                "subscribers": subscribers,
                "pushes_received": delivered,
                "resumes": resumes,
                "subscriber_errors": sub_errors,
                "subs_active": sig.get("push_subs_active"),
                "deltas_per_s": (fresh["deltas"] / fresh["dt_s"]
                                 if fresh["dt_s"] > 0 else 0.0),
                "p99_ms": (round(p99_s * 1e3, 3)
                           if p99_s is not None else None),
                "p99_budget_ms": push_p99_ms,
                "fresh_ok": fresh_ok,
            }
            report["ok"] = bool(report["ok"] and fresh_ok)
        if out_path:
            with open(out_path, "w") as f:
                json.dump(report, f, indent=1, default=str)
                f.write("\n")
            report["report_path"] = os.path.abspath(out_path)
        return report
    finally:
        sampler_stop.set()
        if watcher is not None:
            try:
                watcher.stop()
            except Exception:
                pass
        if autoscaler is not None:
            try:
                autoscaler.stop()
            except Exception:
                pass
        if edge_procs:
            try:
                from ..serve.edge import stop_edge_procs
                stop_edge_procs(edge_procs)
            except Exception:
                pass
        if ctl is not None:
            try:
                ctl.stop(drop_topology=True)
            except Exception:
                pass
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        import shutil
        shutil.rmtree(base, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    from . import slo as obs_slo
    from ..core.params import Params

    params = Params.from_args(sys.argv[1:] if argv is None else argv)
    if not (params.has("rehearsal") or params.has("group")):
        print(__doc__)
        return 2
    weights = (VerbMix.from_string(params.get("mix")).to_dict()
               if params.has("mix") else None)
    duration = float(params.get("durationS", "12"))
    # split the duration 2:3:4:3 across warm/ramp/burst/cool
    report = run_rehearsal(
        out_path=params.get("out", "SLO_REPORT.json"),
        shards=params.get_int("shards", 2),
        replication=params.get_int("replication", 2),
        users=params.get_int("users", 400),
        base_qps=float(params.get("baseQps", "120")),
        peak_qps=float(params.get("peakQps", "240")),
        burst_qps=float(params.get("burstQps", "480")),
        warm_s=duration * 2 / 12, ramp_s=duration * 3 / 12,
        burst_s=duration * 4 / 12, cool_s=duration * 3 / 12,
        threads=params.get_int("threads", 4),
        seed=params.get_int("seed", 0),
        verb_weights=weights,
        autoscale=params.get("autoscale", "live"),
        kill=params.get_int("kill", 1) != 0,
        group=params.get("newGroup", "rehearsal"),
        attach_group=params.get("group", None),
        zipf_exponent=float(params.get("zipf", "1.1")),
        abusive_qps=float(params.get("abusiveQps", "0")),
        watch=params.get_int("watch", 0) != 0,
        edge=params.get_int("edge", 0),
        subscribers=params.get_int("subscribers", 0),
        push_p99_ms=float(params.get("pushP99Ms", "250")),
    )
    sys.stderr.write(obs_slo.human_summary(report) + "\n")
    out = {
        "ok": report["ok"],
        "report": report.get("report_path"),
        "verbs": {v: {"availability": s["availability"],
                      "p99_ms": s["p99_ms"]}
                  for v, s in report["verbs"].items()},
        "breaches": len(report["breaches"]),
        "unattributed_errors": report["errors"]["unattributed"],
    }
    if "alerts" in report:
        out["alerts"] = {k: report["alerts"][k] for k in
                         ("fired_total", "unattributed_page", "detection")}
    if "push" in report:
        out["push"] = {k: report["push"][k] for k in
                       ("pushes_received", "p99_ms", "fresh_ok")}
    print(json.dumps(out, indent=1))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
