"""Continuous fleet watch loop: scrape -> retain -> evaluate -> page.

The missing live half of the observability stack.  ``FleetWatcher`` runs
a fixed-cadence loop (``TPUMS_WATCH_INTERVAL_S``, default 2 s):

1. **scrape** the whole fleet concurrently (``scrape.scrape_fleet`` —
   one wedged replica costs one timeout, not the cadence);
2. **retain** the merge plus derived watch series in a bounded ring
   store (``tsdb.SeriesStore`` — wall-clock + point-count eviction,
   optional JSONL spill for post-mortem);
3. **probe** live model quality on its own sub-cadence
   (``ModelQualityCanary`` — a held-out ratings slice scored against the
   LIVE fleet through the same grouping/skip semantics as ``eval/mse``,
   published as ``tpums_model_live_mse`` / ``tpums_model_staleness_seconds``
   / ``tpums_probe_coverage`` — the drift signal ROADMAP item 2's
   autopilot consumes);
4. **evaluate** the declarative rules engine (``rules.RulesEngine`` —
   thresholds, absence, multi-window burn rate, ``for:`` hold-down,
   flap suppression) and emit every transition as a tracing event;
5. **publish** the alert summary outward: ``tpums_alerts_firing`` /
   ``tpums_alerts_max_severity`` gauges in the process metrics registry
   (so a co-located server exports them over METRICS) and a TTL'd
   registry alert record (so HEALTH hints and out-of-process
   ``fleet_signals`` callers see the same state).

Every firing is attributed to the nearest disruptive event (kill,
cutover, rollout, autoscale decision) with the SLO report's own
machinery; ``watch_summary()["unattributed_page"] == 0`` is the chaos
gate — nothing paged that the run cannot explain.  ``detection_latencies``
pairs kill events with their first subsequent page, which is the bound
``scripts/chaos_kill.py`` records.

CLI::

    python -m flink_ms_tpu.obs.watch                  # watch until ^C
    python -m flink_ms_tpu.obs.watch --once           # one tick -> JSON
    python -m flink_ms_tpu.obs.watch --rules r.json --duration 60
    python -m flink_ms_tpu.obs.watch --prom           # + text exposition
    python -m flink_ms_tpu.obs.watch --spill w.jsonl  # post-mortem trail
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from ..serve import registry as job_registry
from . import forensics
from . import profdiff
from . import tracing
from .metrics import get_registry, render_prometheus
from .profiler import CPU_SECONDS_SERIES
from .rules import (RulesEngine, attribute_alerts, default_rules,
                    load_rules)
from .scrape import scrape_fleet, scrape_fleet_profiles
from .slo import DEFAULT_ATTRIBUTION_WINDOW_S, DISRUPTIVE_KINDS
from .tsdb import SeriesStore

__all__ = ["FleetWatcher", "ModelQualityCanary", "DEFAULT_INTERVAL_S",
           "KILL_KINDS", "main"]

DEFAULT_INTERVAL_S = 2.0
DEFAULT_SCOPE = "fleet"

# the kill-shaped subset of the disruptive kinds: what detection latency
# is measured against
KILL_KINDS = frozenset({"chaos_kill", "chaos_kill_warming",
                        "rehearsal_kill"})

# matches serve/consumer.py ALS_STATE — string, not import, so the obs
# layer stays importable without the serving stack (same stance as slo's
# ADMISSION_SHED_MARKER)
_DEFAULT_STATE = "ALS_MODEL"


def _env_float(name: str, default: float, lo: float) -> float:
    try:
        return max(float(os.environ.get(name, default)), lo)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# model-quality canary
# ---------------------------------------------------------------------------

class ModelQualityCanary:
    """Live held-out-quality prober.

    Holds a probe slice of ratings (an evenly-strided sample of what the
    caller provides, capped at ``max_probe`` so a probe is a handful of
    MGETs, not an eval job), scores it against the live fleet through
    ``eval.mse.compute_mse`` — the SAME grouping/skip semantics as the
    offline evaluator, so live and offline MSE on one slice are the
    identical statistic — and publishes three gauges:

    - ``tpums_model_live_mse``: the probe's MSE (absent until something
      scores);
    - ``tpums_probe_coverage``: scored fraction of the probe slice (a
      coverage collapse means keys vanished — a bad rollout looks like
      this before quality numbers move);
    - ``tpums_model_staleness_seconds``: seconds since the fetched
      factors last CHANGED (fingerprint of the raw payloads) — the
      online-update loop's liveness, measured from the serving side.

    ``client`` is anything with ``query_states(name, keys)`` (QueryClient,
    HAShardedClient, ElasticClient) or a zero-arg factory returning one
    (resolved lazily, so the canary can be built before the fleet is up).
    """

    def __init__(self, users, items, ratings,
                 client, state_name: str = _DEFAULT_STATE,
                 max_probe: int = 512):
        users = np.asarray(users)
        items = np.asarray(items)
        ratings = np.asarray(ratings, dtype=np.float64)
        if not (len(users) == len(items) == len(ratings)):
            raise ValueError("users/items/ratings length mismatch")
        if len(ratings) == 0:
            raise ValueError("empty probe slice")
        if len(ratings) > max_probe:
            idx = np.linspace(0, len(ratings) - 1, max_probe).astype(int)
            users, items, ratings = users[idx], items[idx], ratings[idx]
        self.users, self.items, self.ratings = users, items, ratings
        self.state_name = state_name
        self._client_or_factory = client
        self._client = None
        self._fingerprint: Optional[str] = None
        self._fingerprint_ts: Optional[float] = None
        self.probes = 0
        self.last: Optional[dict] = None

    def _resolve_client(self):
        if self._client is None:
            c = self._client_or_factory
            self._client = c if hasattr(c, "query_states") else c()
        return self._client

    @staticmethod
    def _parse(payload: Optional[str]):
        if payload is None:
            return None
        # serving values are the factor payload "f1;f2;..."
        return np.asarray([float(t) for t in payload.split(";") if t])

    def probe(self, now: Optional[float] = None) -> dict:
        """One probe round -> ``{"mse", "n_scored", "n_skipped",
        "coverage", "staleness_s", "ts"}``; also sets the three gauges."""
        from ..eval.mse import compute_mse

        now = time.time() if now is None else now
        client = self._resolve_client()
        fetched: List[str] = []

        def lookup_many(keys):
            payloads = client.query_states(self.state_name, list(keys))
            fetched.extend(p for p in payloads if p is not None)
            return [self._parse(p) for p in payloads]

        def lookup(key):
            return lookup_many([key])[0]

        mse, n_scored, n_skipped = compute_mse(
            self.users, self.items, self.ratings, lookup,
            lookup_many=lookup_many)
        coverage = n_scored / len(self.ratings)
        fp = hashlib.sha1(
            "\n".join(sorted(fetched)).encode()).hexdigest() \
            if fetched else None
        if fp != self._fingerprint:
            self._fingerprint = fp
            self._fingerprint_ts = now
        staleness = (now - self._fingerprint_ts
                     if self._fingerprint_ts is not None else 0.0)
        reg = get_registry()
        if mse is not None:
            reg.gauge("tpums_model_live_mse").set(mse)
        reg.gauge("tpums_probe_coverage").set(coverage)
        reg.gauge("tpums_model_staleness_seconds").set(staleness)
        self.probes += 1
        self.last = {"mse": mse, "n_scored": n_scored,
                     "n_skipped": n_skipped, "coverage": coverage,
                     "staleness_s": staleness, "ts": now}
        return self.last

    @classmethod
    def from_ratings_file(cls, path: str, client,
                          state_name: str = _DEFAULT_STATE,
                          max_probe: int = 512,
                          field_delimiter: str = "tab"
                          ) -> "ModelQualityCanary":
        """Build the probe slice from a ratings file (the same reader the
        trainers/evaluators use)."""
        from ..core import formats as F
        users, items, ratings = F.read_ratings(
            path, field_delimiter=field_delimiter, ignore_first_line=True)
        return cls(users, items, ratings, client, state_name=state_name,
                   max_probe=max_probe)


# ---------------------------------------------------------------------------
# the watch loop
# ---------------------------------------------------------------------------

def _exemplar_tids(scrape: dict, series: str,
                   limit: int = 8) -> List[str]:
    """Trace ids retained by the fleet's exemplar-linked histogram
    buckets for ``series``, slowest bucket first — the concrete requests
    behind a breached latency quantile."""
    recs = []  # (bucket_index, value, tid)
    fleet = scrape.get("fleet") or {}
    for h in fleet.get("histograms", []):
        if h.get("name") != series:
            continue
        for idx, rec in (h.get("exemplars") or {}).items():
            try:
                recs.append((int(idx), float(rec[1]), str(rec[0])))
            except (TypeError, ValueError, IndexError):
                continue
    recs.sort(key=lambda r: (-r[0], -r[1]))
    out: List[str] = []
    for _, _, tid in recs:
        if tid not in out:
            out.append(tid)
        if len(out) >= limit:
            break
    return out


class FleetWatcher:
    """Scrape/retain/evaluate/publish on a fixed cadence (see module
    docstring).  Use as a context manager or ``start()``/``stop()``;
    ``tick()`` is public so tests and ``--once`` drive it synchronously."""

    def __init__(self,
                 interval_s: Optional[float] = None,
                 rules=None,
                 store: Optional[SeriesStore] = None,
                 canary: Optional[ModelQualityCanary] = None,
                 canary_every: int = 1,
                 scope: str = DEFAULT_SCOPE,
                 spill_path: Optional[str] = None,
                 scrape_timeout_s: Optional[float] = None,
                 publish: bool = True,
                 attribution_window_s: float =
                 DEFAULT_ATTRIBUTION_WINDOW_S,
                 profile_attach: bool = True):
        self.interval_s = (
            _env_float("TPUMS_WATCH_INTERVAL_S", DEFAULT_INTERVAL_S, 0.05)
            if interval_s is None else max(float(interval_s), 0.05))
        if rules is None:
            rules_path = os.environ.get("TPUMS_WATCH_RULES", "").strip()
            rules = load_rules(rules_path) if rules_path \
                else default_rules()
        spill_path = spill_path or \
            os.environ.get("TPUMS_WATCH_SPILL", "").strip() or None
        self.store = store if store is not None else \
            SeriesStore(spill_path=spill_path)
        if spill_path and self.store.spill_path is None:
            self.store.spill_path = spill_path
        self.engine = RulesEngine(rules)
        self.canary = canary
        self.canary_every = max(int(canary_every), 1)
        self.scope = scope
        self.scrape_timeout_s = (
            _env_float("TPUMS_WATCH_SCRAPE_TIMEOUT_S", 1.0, 0.05)
            if scrape_timeout_s is None else float(scrape_timeout_s))
        self.publish = publish
        self.attribution_window_s = attribution_window_s
        # continuous-profiling attachment: each tick keeps the fleet
        # profile so a CPU/quantile firing can be diffed prev-vs-now and
        # page WITH the top-delta frames (profdiff), not just the number
        self.profile_attach = profile_attach
        self._prof_prev: Optional[dict] = None
        self.ticks = 0
        self.last_scrape: Optional[dict] = None
        self.last_error: Optional[str] = None
        self.tick_seconds: deque = deque(maxlen=512)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one tick ---------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One scrape/probe/evaluate/publish round -> this tick's alert
        transitions."""
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        scrape = scrape_fleet(timeout_s=self.scrape_timeout_s)
        self.last_scrape = scrape
        self.store.ingest_fleet(scrape, ts=now)
        if self.canary is not None and self.ticks % self.canary_every == 0:
            try:
                p = self.canary.probe(now=now)
            except (OSError, RuntimeError, ValueError) as e:
                # a probe outage is a signal (absence rules see the gap),
                # never a watch-loop crash
                self.last_error = f"canary: {e}"
            else:
                if p["mse"] is not None:
                    self.store.observe("tpums_model_live_mse", p["mse"],
                                       ts=now)
                self.store.observe("tpums_probe_coverage", p["coverage"],
                                   ts=now)
                self.store.observe("tpums_model_staleness_seconds",
                                   p["staleness_s"], ts=now)
        transitions = self.engine.evaluate(self.store, now=now)
        prof_cur = None
        if self.profile_attach:
            try:
                prof_cur = scrape_fleet_profiles(
                    timeout_s=self.scrape_timeout_s)["fleet"]
            except Exception as e:  # noqa: BLE001 - never kill the tick
                self.last_error = f"profile scrape: {e}"
        if transitions:
            self._attach_forensics(transitions, scrape)
            self._attach_profile(transitions, prof_cur)
        if prof_cur is not None:
            self._prof_prev = prof_cur
        if self.publish:
            summary = self.engine.summary()
            reg = get_registry()
            reg.gauge("tpums_alerts_firing").set(summary["firing"])
            reg.gauge("tpums_alerts_max_severity").set(
                summary["max_severity_level"])
            reg.gauge("tpums_watch_scrape_duration_seconds").set(
                scrape.get("scrape_duration_s") or 0.0)
            job_registry.publish_alerts(
                self.scope, summary,
                ttl_s=max(5.0 * self.interval_s, 15.0))
        self.ticks += 1
        self.tick_seconds.append(time.perf_counter() - t0)
        return transitions

    def _attach_forensics(self, transitions: List[dict],
                          scrape: dict) -> None:
        """Enrich latency-quantile firings with forensics: the exemplar
        tids the breached histogram retained, plus each trace's critical
        path.  The incident record then NAMES the stage that made p99
        slow instead of just quoting the breached number.  Transitions
        are the same dict objects ``engine.history`` keeps, so the
        enrichment lands in the incident timeline."""
        rules = {r.name: r for r in self.engine.rules}
        for tr in transitions:
            rule = rules.get(tr.get("rule"))
            if (tr.get("kind") != "alert_firing" or rule is None
                    or rule.kind != "threshold"
                    or rule.mode != "quantile"):
                continue
            tids = _exemplar_tids(scrape, rule.series)
            if not tids:
                continue
            spill = tracing.trace_file_path()
            try:
                ctx = forensics.incident_context(
                    tids, paths=[spill] if spill else None)
            except (OSError, ValueError) as e:
                self.last_error = f"forensics: {e}"
                continue
            tr.update(ctx)

    def _attach_profile(self, transitions: List[dict],
                        prof_cur: Optional[dict]) -> None:
        """Enrich CPU-regression and latency-quantile firings with the
        profiling plane: diff the PREVIOUS tick's fleet profile against
        this tick's and attach the top-delta frames, so the page names
        the code that got hot (``profile_top_frames``), completing the
        alert -> stage (forensics) -> frames (profdiff) chain.  First
        tick has no baseline; the firing still pages, just unframed."""
        if prof_cur is None or self._prof_prev is None:
            return
        rules = {r.name: r for r in self.engine.rules}
        frames: Optional[List[dict]] = None
        for tr in transitions:
            rule = rules.get(tr.get("rule"))
            if (tr.get("kind") != "alert_firing" or rule is None
                    or rule.kind != "threshold"
                    or (rule.mode != "quantile"
                        and rule.series != CPU_SECONDS_SERIES)):
                continue
            if frames is None:
                try:
                    frames = profdiff.top_frames(self._prof_prev, prof_cur)
                except (ValueError, TypeError) as e:
                    self.last_error = f"profdiff: {e}"
                    return
            if frames:
                tr["profile_top_frames"] = frames

    def _run(self) -> None:
        while not self._stop.is_set():
            t0 = time.time()
            try:
                self.tick(now=t0)
            except Exception as e:  # noqa: BLE001 - loop must survive
                self.last_error = f"{type(e).__name__}: {e}"
            self._stop.wait(max(self.interval_s - (time.time() - t0),
                                0.01))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "FleetWatcher":
        if self._thread is not None:
            raise RuntimeError("watcher already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpums-watch")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, 3 * self.interval_s))
            self._thread = None
        if self.publish:
            job_registry.drop_alerts(self.scope)

    def __enter__(self) -> "FleetWatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- incident timeline ------------------------------------------------

    def _disruptive_events(self) -> List[dict]:
        # only events from THIS watcher's lifetime: the tracing ring is
        # process-global and may hold kills from earlier runs (tests,
        # repeated bench sections) that this watcher never saw
        t0 = self.engine.started_at
        return [e for e in tracing.recent_events()
                if e.get("kind") in DISRUPTIVE_KINDS
                and e.get("ts", 0.0) >= t0]

    def incident_timeline(self) -> List[dict]:
        """Disruptive events and alert transitions merged, time-ordered —
        the live counterpart of the SLO report's timeline."""
        merged = self._disruptive_events() + list(self.engine.history)
        return sorted(merged, key=lambda e: e.get("ts", 0.0))

    def attribution(self) -> dict:
        """Attribute every firing so far to the nearest disruptive event
        (``{"alerts", "unattributed", "unattributed_page", "window_s"}``)."""
        return attribute_alerts(self.engine.history,
                                self._disruptive_events(),
                                window_s=self.attribution_window_s)

    def detection_latencies(self,
                            kill_kinds: Sequence[str] = tuple(KILL_KINDS)
                            ) -> dict:
        """kill -> first subsequent page-severity firing inside the
        attribution window, each page consumed by AT MOST one kill (so a
        single page cannot "detect" several kills, and a page long after
        a kill does not count as detecting it)::

            {"kills": N, "detected": M, "latencies_s": [...],
             "max_s": worst | None}
        """
        kinds = frozenset(kill_kinds)
        t0 = self.engine.started_at
        kills = sorted(e["ts"] for e in tracing.recent_events()
                       if e.get("kind") in kinds
                       and e.get("ts", 0.0) >= t0)
        pages = sorted(tr["ts"] for tr in self.engine.history
                       if tr["kind"] == "alert_firing"
                       and tr.get("severity") == "page")
        latencies: List[float] = []
        detected = 0
        pi = 0
        for k_ts in kills:
            while pi < len(pages) and pages[pi] < k_ts:
                pi += 1
            if pi < len(pages) and \
                    pages[pi] - k_ts <= self.attribution_window_s:
                detected += 1
                latencies.append(round(pages[pi] - k_ts, 3))
                pi += 1
        return {"kills": len(kills), "detected": detected,
                "latencies_s": latencies,
                "max_s": max(latencies) if latencies else None}

    def watch_summary(self) -> dict:
        """The artifact section chaos/bench runs record."""
        s = self.engine.summary()
        att = self.attribution()
        det = self.detection_latencies()
        fired = sum(1 for t in self.engine.history
                    if t["kind"] == "alert_firing")
        resolved = sum(1 for t in self.engine.history
                       if t["kind"] == "alert_resolved")
        return {
            "ticks": self.ticks,
            "interval_s": self.interval_s,
            "firing": s["firing"],
            "max_severity": s["max_severity"],
            "fired_total": fired,
            "resolved_total": resolved,
            "unattributed": att["unattributed"],
            "unattributed_page": att["unattributed_page"],
            "detection": det,
            "canary": self.canary.last if self.canary else None,
            "avg_tick_s": round(
                sum(self.tick_seconds) / len(self.tick_seconds), 6)
            if self.tick_seconds else None,
            "store": self.store.stats(),
            "last_error": self.last_error,
        }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flink_ms_tpu.obs.watch",
        description="continuous fleet watch loop")
    ap.add_argument("--rules", help="JSON rules file (default: built-in "
                                    "fleet baseline or TPUMS_WATCH_RULES)")
    ap.add_argument("--interval", type=float, default=None,
                    help="scrape cadence seconds "
                         "(TPUMS_WATCH_INTERVAL_S, default 2)")
    ap.add_argument("--duration", type=float, default=None,
                    help="watch for N seconds then summarize "
                         "(default: until interrupted)")
    ap.add_argument("--once", action="store_true",
                    help="single tick, print the summary, exit")
    ap.add_argument("--prom", action="store_true",
                    help="also print text exposition of the last fleet "
                         "merge + watch gauges")
    ap.add_argument("--spill", help="JSONL spill path (TPUMS_WATCH_SPILL)")
    ap.add_argument("--scope", default=DEFAULT_SCOPE,
                    help="registry alert-record scope (default: fleet)")
    args = ap.parse_args(argv)

    rules = load_rules(args.rules) if args.rules else None
    w = FleetWatcher(interval_s=args.interval, rules=rules,
                     spill_path=args.spill, scope=args.scope)
    transitions: List[dict] = []
    if args.once:
        transitions = w.tick()
    else:
        w.start()
        try:
            if args.duration is not None:
                time.sleep(args.duration)
            else:
                while True:
                    time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            w.stop()
    summary = w.watch_summary()
    summary["transitions"] = transitions if args.once \
        else w.engine.history
    print(json.dumps(summary, indent=2, default=str))
    if args.prom:
        if w.last_scrape is not None:
            sys.stdout.write(render_prometheus(w.last_scrape["fleet"]))
        sys.stdout.write(render_prometheus(get_registry().snapshot()))
    job_registry.drop_alerts(args.scope)
    return 0 if summary["unattributed_page"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
