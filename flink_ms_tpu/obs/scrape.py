"""Fleet scraper — walk the job registry, pull every live replica's
METRICS snapshot, aggregate per shard and fleet-wide.

This is the pull half of the Prometheus model applied to our file-based
registry: the registry already knows every live endpoint (heartbeat TTLs
GC the dead ones), so a scrape is ``list_jobs()`` + one ``METRICS`` verb
round-trip per entry — no push agents, no sidecar config.  Aggregation is
``metrics.merge_snapshots`` (sum counters/gauges, add histogram buckets),
grouped by the ``replica_of`` shard-group id when present, so the output
answers both "what is shard 1's p99" and "what is the fleet's p99" from
one pass.

Usable as a library (``scrape_fleet()`` — obs_smoke, tests, bench) and as
a CLI::

    python -m flink_ms_tpu.obs.scrape            # aggregated JSON
    python -m flink_ms_tpu.obs.scrape --prom     # Prometheus exposition
    python -m flink_ms_tpu.obs.scrape --raw      # per-replica snapshots
"""

from __future__ import annotations

import json
import socket
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from ..serve import registry
from .metrics import (LATENCY_BUCKETS_S, merge_snapshots, render_prometheus,
                      snapshot_quantile)
from .profiler import merge_profiles, scrape_profile

__all__ = ["scrape_endpoint", "scrape_fleet", "fleet_signals",
           "scrape_fleet_profiles", "snapshot_quantile", "main"]

# scrape fan-out width: enough that one wedged endpoint can't stretch the
# scrape past ~one timeout even on a wide fleet, small enough that a
# watch tick doesn't spawn a thread herd
_SCRAPE_POOL_MAX = 16


def scrape_endpoint(host: str, port: int, timeout_s: float = 2.0
                    ) -> Optional[dict]:
    """One METRICS round-trip -> parsed snapshot dict, or None when the
    endpoint is unreachable or doesn't speak the verb.  Both planes speak
    it: the C++ native server (round 8) exports per-verb series on the
    same bucket ladder, tagged ``meta.plane = "native"``."""
    host = host or "localhost"
    if host == "0.0.0.0":
        host = "localhost"
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout_s) as sock:
            sock.sendall(b"METRICS\n")
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(1 << 16)
                if not chunk:
                    break
                buf += chunk
    except OSError:
        return None
    line = buf.decode("utf-8", "replace").strip()
    if not line.startswith("J\t"):
        return None
    try:
        snap = json.loads(line[2:])
    except ValueError:
        return None
    return snap if isinstance(snap, dict) else None


def scrape_fleet(timeout_s: float = 2.0) -> dict:
    """Scrape every live registry entry CONCURRENTLY and aggregate.

    Returns::

        {"replicas":  [{"job_id", "shard_group", "replica", "ready",
                        "host", "port", "snapshot"|None,
                        "stale", "scrape_s"}, ...],
         "per_shard": {shard_group: merged-snapshot, ...},
         "fleet":     merged-snapshot,
         "scraped": N, "unreachable": M,
         "scrape_duration_s": wall seconds for the whole fan-out}

    Replica polls run on a small thread pool with the per-endpoint
    ``timeout_s``, so one dead or wedged replica costs the scrape ONE
    timeout instead of serially stalling the cadence behind it; a replica
    that failed to answer carries ``stale: True`` (its last-known state
    may still exist in a retained store) and ``scrape_s`` records its
    individual round-trip.

    ``shard_group`` falls back to the job_id for unsharded jobs, so a
    single standalone worker still aggregates sanely.

    Native-plane snapshots (``meta.plane == "native"``) are REQUIRED to
    carry the shared latency ladder: ``merge_snapshots`` silently skips a
    histogram whose bounds disagree, which for a native worker would mean
    the autoscaler's p99 quietly loses a whole plane's traffic — that is a
    build-skew bug, so it raises here instead of degrading.
    """
    t_start = time.time()
    entries = registry.list_jobs()
    expected_le = list(LATENCY_BUCKETS_S)

    def poll(entry: dict) -> tuple:
        t0 = time.time()
        snap = scrape_endpoint(entry.get("host", "localhost"),
                               entry["port"], timeout_s=timeout_s)
        return snap, time.time() - t0

    if entries:
        with ThreadPoolExecutor(
                max_workers=min(len(entries), _SCRAPE_POOL_MAX),
                thread_name_prefix="tpums-scrape") as pool:
            polled = list(pool.map(poll, entries))
    else:
        polled = []

    replicas: List[dict] = []
    per_group: Dict[str, List[dict]] = {}
    unreachable = 0
    for entry, (snap, scrape_s) in zip(entries, polled):
        if snap is not None and (
                snap.get("meta", {}).get("plane") == "native"):
            for h in snap.get("histograms", []):
                if (h.get("name") == "tpums_server_latency_seconds"
                        and list(h.get("le", [])) != expected_le):
                    raise ValueError(
                        f"native worker {entry.get('job_id')!r} "
                        f"({entry.get('host')}:{entry.get('port')}) exports "
                        "tpums_server_latency_seconds with foreign bucket "
                        "bounds — native/Python build skew; rebuild "
                        "libtpums.so against this obs/metrics.py")
        group = entry.get("replica_of") or entry.get("job_id", "?")
        replicas.append({
            "job_id": entry.get("job_id"),
            "shard_group": group,
            "replica": entry.get("replica"),
            "ready": entry.get("ready"),
            "host": entry.get("host"),
            "port": entry.get("port"),
            "snapshot": snap,
            "stale": snap is None,
            "scrape_s": round(scrape_s, 6),
        })
        if snap is None:
            unreachable += 1
        else:
            per_group.setdefault(group, []).append(snap)
    all_snaps = [s for snaps in per_group.values() for s in snaps]
    return {
        "replicas": replicas,
        "per_shard": {g: merge_snapshots(s) for g, s in per_group.items()},
        "fleet": merge_snapshots(all_snaps),
        "scraped": len(all_snaps),
        "unreachable": unreachable,
        "scrape_duration_s": round(time.time() - t_start, 6),
    }


def scrape_fleet_profiles(timeout_s: float = 2.0) -> dict:
    """``scrape_fleet`` for the continuous-profiling plane: one PROFILE
    round-trip per live registry entry, merged with the associative
    ``profiler.merge_profiles`` fold (per-stack seconds sum — exactly how
    METRICS snapshots merge through ``merge_snapshots``).

    Returns::

        {"replicas": [{"job_id", "host", "port", "profile"|None}, ...],
         "fleet":    merged profile (Python sample-seconds and native
                     per-verb CPU self-time in ONE stacks dict),
         "scraped": N, "unreachable": M, "scrape_duration_s": ...}

    A replica that answers METRICS but not PROFILE (pre-profiler build)
    counts unreachable here but is NOT an error — the fleet profile is
    simply missing that plane until its next rollout."""
    t_start = time.time()
    entries = registry.list_jobs()

    def poll(entry: dict) -> Optional[dict]:
        return scrape_profile(entry.get("host", "localhost"),
                              entry["port"], timeout_s=timeout_s)

    if entries:
        with ThreadPoolExecutor(
                max_workers=min(len(entries), _SCRAPE_POOL_MAX),
                thread_name_prefix="tpums-profscrape") as pool:
            polled = list(pool.map(poll, entries))
    else:
        polled = []

    replicas = []
    profiles = []
    for entry, prof in zip(entries, polled):
        replicas.append({"job_id": entry.get("job_id"),
                         "host": entry.get("host"),
                         "port": entry.get("port"),
                         "profile": prof})
        if prof is not None:
            profiles.append(prof)
    return {
        "replicas": replicas,
        "fleet": merge_profiles(profiles),
        "scraped": len(profiles),
        "unreachable": len(entries) - len(profiles),
        "scrape_duration_s": round(time.time() - t_start, 6),
    }


# verbs that are plumbing, not user traffic — excluded from the qps signal
# so a scrape/health poller can't talk an autoscaler into scaling out
_NON_QUERY_VERBS = frozenset({"HEALTH", "METRICS", "PING", "PROFILE",
                              "SUBSCRIBE", "RESUME", "UNSUB"})


def _query_hists(snapshot: dict) -> List[dict]:
    return [h for h in snapshot.get("histograms", [])
            if h["name"] == "tpums_server_latency_seconds"
            and h.get("labels", {}).get("verb") not in _NON_QUERY_VERBS]


def fleet_signals(before: dict, after: dict,
                  dt_s: Optional[float] = None) -> dict:
    """Autoscaler inputs from two fleet snapshots (``scrape_fleet()``'s
    ``fleet`` merges) taken ``dt_s`` apart (defaults to the snapshots' own
    timestamp delta)::

        {"qps":            query verbs/s over the window (HEALTH/METRICS/
                           PING excluded — pollers must not look like load),
         "p99_s":          interpolated p99 of the window's query-verb
                           latency observations (None with no traffic),
         "backlog_bytes":  fleet ingest backlog at AFTER (gauge level),
         "shed_per_s":     admission-shed requests/s over the window
                           (``tpums_admission_shed_total`` delta across
                           all tenants/verbs — serve/admission.py),
         "admission_pressure": worst per-tenant bucket drain in [0, 1]
                           at AFTER (max ``tpums_admission_pressure``,
                           saturating at 1.0 — fleet merges sum the
                           gauge across replicas of the same tenant),
         "dt_s", "requests": the window itself}

    The admission fields make the shedder and the autoscaler act on the
    same numbers: sustained shed with low pressure elsewhere means a hot
    tenant, shed AND high qps means the fleet itself needs more shards.

    Retrieval-plane health (round 11 — ``serve/topk.py``/``serve/ann.py``
    maintenance):

        {"topk_rebuilds_per_s": full index rebuilds/s over the window
                           (``tpums_topk_rebuilds_total`` delta — a
                           sustained rate means structural churn is
                           outrunning the incremental scatter path),
         "topk_dirty_depth": fleet-summed dirty backlog at AFTER
                           (unabsorbed streaming updates),
         "topk_staleness_s": WORST per-process index staleness at AFTER
                           (the gauge is pid-labeled precisely so this
                           can be a max — a fleet SUM of stalenesses
                           means nothing),
         "ann_recall":     worst measured IVF recall probe across the
                           fleet at AFTER (min over pid-labeled
                           ``tpums_ann_recall_probe`` series; None when
                           no replica has an ANN tier built)}

    Watch-plane state (round 12 — ``obs/watch.py``):

        {"alerts_firing": currently-firing alert count (the watcher's
                          ``tpums_alerts_firing`` gauge when present in
                          AFTER, else the registry's published alert
                          record),
         "alerts_max_severity": "info"/"warn"/"page" or None}

    Autopilot progress (round 13 — ``serve/autopilot.py``):

        {"autopilot_retrains":  retrains completed over the window
                           (``tpums_autopilot_retrains_total`` delta),
         "autopilot_rollouts":  automatic rollouts over the window,
         "autopilot_rollbacks": drift-triggered rollbacks over the window,
         "autopilot_heldout_mse": newest candidate's held-out MSE at
                           AFTER (min across processes; None until an
                           evaluation has run)}

    Geo-replication plane (round 15 — ``serve/georepl.py``):

        {"georepl_lag_bytes":   fleet-summed un-replicated journal
                           backlog at AFTER (``tpums_georepl_lag_bytes``
                           across topics/regions),
         "georepl_lag_seconds": WORST follower staleness at AFTER (max
                           over ``tpums_georepl_lag_seconds`` — a fleet
                           sum of times means nothing)}

    Shared-memory arena plane (round 16 — ``serve/arena.py``):

        {"arena_resident_bytes": fleet-summed resident arena pages at
                           AFTER (both the Python writer's gauge and the
                           C++ server's METRICS splice feed this),
         "arena_read_retries_per_s": seqlock read retries/s over the
                           window — sustained retries mean hot-row write
                           contention on the lock-free read path,
         "arena_load_factor": WORST index load factor at AFTER (growth/
                           rehash predictor),
         "arena_publish_seconds": newest O(state) snapshot publish
                           latency (max across workers; None until an
                           arena snapshot has published)}

    Native write plane (round 17 — ``native/arena.cpp`` batch writer +
    CAS updates; both the Python writer's registry and the C++ server's
    METRICS splice of the ``writer.stats`` sidecar feed these):

        {"arena_batch_rows_per_s": rows applied by the native columnar
                           batch writer/s over the window — the write
                           path's throughput signal; a fall to ~0 while
                           ingest backlog grows means the native writer
                           degraded to the Python path,
         "arena_cas_success_per_s": in-place CAS swaps/s over the window
                           (the update plane writing at hardware speed),
         "arena_cas_retry_per_s": failed CAS compares/s — sustained
                           retries mean update workers are losing races
                           to the ingest writer and falling back to LWW
                           re-puts}

    Edge proxy tier (round 18 — ``serve/edge.py``; proxies register in
    the registry like workers, so ``scrape_fleet`` reaches them through
    the same METRICS verb):

        {"edge_open_connections": fleet-summed downstream connections
                           held open at AFTER (the tier's fan-in),
         "edge_coalesce_per_s": in-flight GET coalesce hits/s over the
                           window (requests answered WITHOUT an upstream
                           round trip),
         "edge_hedges_per_s": hedged requests fired/s,
         "edge_hedge_wins_per_s": hedges whose backup reply won/s —
                           fired without wins means the trigger is too
                           twitchy; wins without fires is impossible,
         "edge_shed_per_s": edge-side admission sheds/s (refused before
                           any upstream bytes),
         "edge_p99_s":     through-proxy p99 over the proxy's own query
                           verbs at AFTER (same log-bucket ladder as the
                           server's, so edge overhead is one
                           subtraction; None when no proxy served)}

    Push plane (round 20 — ``serve/push.py`` on the workers plus the
    edge hub's fan-out; subscription verbs are in ``_NON_QUERY_VERBS``,
    so a million idle subscribers never look like query load):

        {"push_subs_active": fleet-summed live subscriptions at AFTER
                           (worker-held and proxy-held both feed the
                           same gauge),
         "push_deltas_per_s": worker-emitted deltas/s over the window,
         "push_notifications_per_s": downstream notifications/s out of
                           the edge hubs — notifications/deltas is the
                           realized fan-out amplification,
         "push_fanout_ratio": WORST-case (max) downstream-subs per
                           upstream-sub across proxies at AFTER,
         "push_resumes_per_s": RESUME verbs served/s (replay and
                           snapshot-fallback both count; sustained rate
                           means clients are churning connections),
         "push_ring_evictions_per_s": replay-ring entries dropped/s —
                           nonzero means disconnected subscribers are
                           outliving their rings and will pay a full
                           snapshot on resume,
         "push_p99_s":     update→push p99 at AFTER over the window
                           (``tpums_push_latency_seconds`` — the ladder
                           the SLO_REPORT freshness gate reads)}

    Continuous-profiling plane (round 19 — ``obs/profiler.py``; the
    sampler's flush publishes these, so they ride the normal METRICS
    scrape even though the stacks themselves travel over PROFILE):

        {"prof_samples_per_s": profiler thread-samples/s across the fleet
                           over the window (~hz x threads x replicas when
                           healthy; 0 means the profiler is off or dead),
         "process_cpu_per_s": fleet CPU-seconds burned per wall second
                           over the window (getrusage user+sys deltas —
                           i.e. cores actually busy; the watch plane's
                           CPU-regression rule rates the same counter),
         "native_self_cpu_per_s": CPU-seconds/s spent inside native verb
                           handlers + the native arena write plane (the
                           C++ share of the same picture)}
    """
    if dt_s is None:
        dt_s = max(float(after.get("ts", 0)) - float(before.get("ts", 0)),
                   1e-9)
    b_h = {(h["name"], tuple(sorted(h.get("labels", {}).items()))): h
           for h in _query_hists(before)}
    b_all = {(h["name"], tuple(sorted(h.get("labels", {}).items()))): h
             for h in before.get("histograms", [])}
    requests = 0
    window = None  # delta histogram across all query verbs
    for h in _query_hists(after):
        k = (h["name"], tuple(sorted(h.get("labels", {}).items())))
        prev = b_h.get(k, {"counts": [0] * len(h["counts"]),
                           "count": 0, "sum": 0.0})
        dc = h["count"] - prev["count"]
        if dc <= 0:
            continue
        requests += dc
        dcounts = [a - b for a, b in zip(h["counts"], prev["counts"])]
        if window is None:
            window = {"name": "window", "le": list(h["le"]),
                      "counts": dcounts, "count": dc,
                      "sum": h["sum"] - prev["sum"]}
        elif window["le"] == list(h["le"]):
            window["counts"] = [a + b for a, b in
                                zip(window["counts"], dcounts)]
            window["count"] += dc
            window["sum"] += h["sum"] - prev["sum"]
    backlog = sum(
        g["value"] for g in after.get("gauges", [])
        if g["name"] == "tpums_journal_backlog_bytes"
    )

    def _shed_total(snap: dict) -> float:
        return sum(c["value"] for c in snap.get("counters", [])
                   if c["name"] == "tpums_admission_shed_total")

    shed = max(_shed_total(after) - _shed_total(before), 0.0)
    # the fleet merge SUMS same-labeled gauges across replicas, so a
    # tenant drained on several shards at once overshoots 1.0 — clamp:
    # the signal saturates at "some bucket is empty somewhere"
    pressure = min(max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_admission_pressure"), default=0.0), 1.0)

    def _counter_total(snap: dict, name: str) -> float:
        return sum(c["value"] for c in snap.get("counters", [])
                   if c["name"] == name)

    rebuilds = max(
        _counter_total(after, "tpums_topk_rebuilds_total")
        - _counter_total(before, "tpums_topk_rebuilds_total"), 0.0)
    dirty_depth = sum(
        g["value"] for g in after.get("gauges", [])
        if g["name"] == "tpums_topk_dirty_depth")
    staleness = max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_topk_index_staleness_seconds"), default=0.0)
    recall_series = [g["value"] for g in after.get("gauges", [])
                     if g["name"] == "tpums_ann_recall_probe"]
    # alert state (round 12 — obs/watch.py): preferred source is the
    # watcher's own gauges when the watch loop runs inside a scraped
    # process; otherwise fall back to the registry's published alert
    # record, which is how an out-of-process watcher reaches autoscaler
    # callers of this function
    firing = [g["value"] for g in after.get("gauges", [])
              if g["name"] == "tpums_alerts_firing"]
    sev = [g["value"] for g in after.get("gauges", [])
           if g["name"] == "tpums_alerts_max_severity"]
    if firing:
        alerts_firing = sum(firing)
        alerts_sev_level = max(sev) if sev else 0
    else:
        rec = registry.resolve_alerts()
        alerts_firing = rec.get("firing", 0) if rec else 0
        alerts_sev_level = rec.get("max_severity_level", 0) if rec else 0
    try:
        from .rules import severity_name
        alerts_max_severity = (severity_name(alerts_sev_level)
                               if alerts_sev_level else None)
    except ImportError:  # pragma: no cover - rules is stdlib-only
        alerts_max_severity = None
    # autopilot loop progress (round 13 — serve/autopilot.py): counter
    # DELTAS over the window (the autoscaler and bench ask "did the
    # flywheel turn", not "how often has it ever turned") plus the latest
    # held-out score when an evaluation has run
    autopilot = {
        f"autopilot_{k}": max(
            _counter_total(after, f"tpums_autopilot_{k}_total")
            - _counter_total(before, f"tpums_autopilot_{k}_total"), 0.0)
        for k in ("retrains", "rollouts", "rollbacks")
    }
    heldout = [g["value"] for g in after.get("gauges", [])
               if g["name"] == "tpums_autopilot_heldout_mse"]
    autopilot["autopilot_heldout_mse"] = min(heldout) if heldout else None
    # tail-forensics plane (round 14): span volume (rate of span records
    # across the fleet), live exemplar retention, and how stale the last
    # forensics collection is (None = never collected anywhere)
    spans = max(
        _counter_total(after, "tpums_trace_spans_total")
        - _counter_total(before, "tpums_trace_spans_total"), 0.0)
    exemplar_count = sum(
        len(h.get("exemplars") or ())
        for h in after.get("histograms", []))
    last_collect = max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_forensics_last_collect_ts"), default=None)
    forensics_staleness = (
        max(time.time() - last_collect, 0.0)
        if last_collect else None)
    # geo-replication plane (round 15 — serve/georepl.py): bytes lag SUMS
    # across topics/regions (total un-replicated backlog), seconds lag is
    # the WORST follower (a fleet sum of times means nothing)
    georepl_lag_bytes = sum(
        g["value"] for g in after.get("gauges", [])
        if g["name"] == "tpums_georepl_lag_bytes")
    georepl_lag_seconds = max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_georepl_lag_seconds"), default=0.0)
    # shared-memory arena plane (round 16 — serve/arena.py): resident
    # bytes SUM across workers (fleet memory footprint), seqlock read
    # retries as a RATE (sustained retries = hot-row write contention on
    # the lock-free read path), index load factor and publish latency as
    # WORST-case maxes (the former predicts growth/rehash, the latter is
    # the O(state) publish promise being kept or broken)
    arena_resident = sum(
        g["value"] for g in after.get("gauges", [])
        if g["name"] == "tpums_arena_resident_bytes")
    arena_retries = max(
        _counter_total(after, "tpums_arena_read_retries_total")
        - _counter_total(before, "tpums_arena_read_retries_total"), 0.0)
    arena_load_factor = max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_arena_index_load_factor"), default=0.0)
    arena_publish_s = max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_arena_publish_seconds"), default=None)
    # native write plane (round 17): batch-writer and CAS counter DELTAS
    # as rates — write-path regressions (native writer degraded, update
    # plane losing CAS races) surface as rate shifts the watch plane can
    # alert on
    batch_rows = max(
        _counter_total(after, "tpums_arena_batch_rows_total")
        - _counter_total(before, "tpums_arena_batch_rows_total"), 0.0)
    cas_success = max(
        _counter_total(after, "tpums_arena_cas_success_total")
        - _counter_total(before, "tpums_arena_cas_success_total"), 0.0)
    cas_retry = max(
        _counter_total(after, "tpums_arena_cas_retry_total")
        - _counter_total(before, "tpums_arena_cas_retry_total"), 0.0)
    # edge proxy tier (round 18 — serve/edge.py): open downstream
    # connections SUM across proxies (the tier's fan-in), coalesce hits /
    # hedges / edge sheds as RATES (tail management doing work vs. sitting
    # idle), and the through-proxy p99 from the proxy's own latency ladder
    # (same log buckets as the server's, so direct-vs-edge overhead is one
    # subtraction)
    edge_conns = sum(
        g["value"] for g in after.get("gauges", [])
        if g["name"] == "tpums_edge_open_connections")
    edge_coalesce = max(
        _counter_total(after, "tpums_edge_coalesce_hits_total")
        - _counter_total(before, "tpums_edge_coalesce_hits_total"), 0.0)
    edge_hedges = max(
        _counter_total(after, "tpums_edge_hedges_total")
        - _counter_total(before, "tpums_edge_hedges_total"), 0.0)
    edge_hedge_wins = max(
        sum(c["value"] for c in after.get("counters", [])
            if c["name"] == "tpums_edge_hedges_total"
            and c.get("labels", {}).get("result") == "won")
        - sum(c["value"] for c in before.get("counters", [])
              if c["name"] == "tpums_edge_hedges_total"
              and c.get("labels", {}).get("result") == "won"), 0.0)
    edge_shed = max(
        _counter_total(after, "tpums_edge_shed_total")
        - _counter_total(before, "tpums_edge_shed_total"), 0.0)
    # continuous-profiling plane (round 19 — obs/profiler.py): sampler
    # liveness and process CPU as RATES; the native handler/write-plane
    # self-time counters give the C++ share of the same CPU picture
    prof_samples = max(
        _counter_total(after, "tpums_prof_samples_total")
        - _counter_total(before, "tpums_prof_samples_total"), 0.0)
    process_cpu = max(
        _counter_total(after, "tpums_process_cpu_seconds_total")
        - _counter_total(before, "tpums_process_cpu_seconds_total"), 0.0)
    native_self = max(
        (_counter_total(after, "tpums_native_self_seconds_total")
         + _counter_total(after, "tpums_arena_write_cpu_seconds_total"))
        - (_counter_total(before, "tpums_native_self_seconds_total")
           + _counter_total(before, "tpums_arena_write_cpu_seconds_total")),
        0.0)
    # push plane (round 20 — serve/push.py + the edge hub): live subs
    # and fan-out as LEVELS, delta/notification/resume/eviction traffic
    # as RATES, and the update→push freshness ladder's window p99
    push_subs = sum(
        g["value"] for g in after.get("gauges", [])
        if g["name"] == "tpums_push_subs_active")
    push_deltas = max(
        _counter_total(after, "tpums_push_deltas_total")
        - _counter_total(before, "tpums_push_deltas_total"), 0.0)
    push_notifications = max(
        _counter_total(after, "tpums_push_notifications_total")
        - _counter_total(before, "tpums_push_notifications_total"), 0.0)
    push_fanout = max(
        (g["value"] for g in after.get("gauges", [])
         if g["name"] == "tpums_push_fanout_ratio"), default=0.0)
    push_resumes = max(
        _counter_total(after, "tpums_push_resume_total")
        - _counter_total(before, "tpums_push_resume_total"), 0.0)
    push_evictions = max(
        _counter_total(after, "tpums_push_ring_evictions_total")
        - _counter_total(before, "tpums_push_ring_evictions_total"), 0.0)
    push_window = None  # delta histogram of update→push latency
    for h in after.get("histograms", []):
        if h["name"] != "tpums_push_latency_seconds":
            continue
        k = (h["name"], tuple(sorted(h.get("labels", {}).items())))
        prev = b_all.get(k, {"counts": [0] * len(h["counts"]),
                             "count": 0, "sum": 0.0})
        dc = h["count"] - prev["count"]
        if dc <= 0:
            continue
        dcounts = [a - b for a, b in zip(h["counts"], prev["counts"])]
        if push_window is None:
            push_window = {"name": "push_window", "le": list(h["le"]),
                           "counts": dcounts, "count": dc,
                           "sum": h["sum"] - prev["sum"]}
        elif push_window["le"] == list(h["le"]):
            push_window["counts"] = [a + b for a, b in
                                     zip(push_window["counts"], dcounts)]
            push_window["count"] += dc
            push_window["sum"] += h["sum"] - prev["sum"]
    edge_window = None  # delta histogram across the proxy's query verbs
    for h in after.get("histograms", []):
        if h["name"] != "tpums_edge_latency_seconds":
            continue
        if h.get("labels", {}).get("verb") in _NON_QUERY_VERBS:
            continue
        k = (h["name"], tuple(sorted(h.get("labels", {}).items())))
        prev = b_all.get(k, {"counts": [0] * len(h["counts"]),
                             "count": 0, "sum": 0.0})
        dc = h["count"] - prev["count"]
        if dc <= 0:
            continue
        dcounts = [a - b for a, b in zip(h["counts"], prev["counts"])]
        if edge_window is None:
            edge_window = {"name": "edge_window", "le": list(h["le"]),
                           "counts": dcounts, "count": dc,
                           "sum": h["sum"] - prev["sum"]}
        elif edge_window["le"] == list(h["le"]):
            edge_window["counts"] = [a + b for a, b in
                                     zip(edge_window["counts"], dcounts)]
            edge_window["count"] += dc
            edge_window["sum"] += h["sum"] - prev["sum"]
    return {
        **autopilot,
        "qps": requests / dt_s,
        "p99_s": snapshot_quantile(window, 99) if window else None,
        "backlog_bytes": backlog,
        "shed_per_s": shed / dt_s,
        "admission_pressure": pressure,
        "topk_rebuilds_per_s": rebuilds / dt_s,
        "topk_dirty_depth": dirty_depth,
        "topk_staleness_s": staleness,
        "ann_recall": min(recall_series) if recall_series else None,
        "alerts_firing": alerts_firing,
        "alerts_max_severity": alerts_max_severity,
        "trace_spans_per_s": spans / dt_s,
        "exemplar_count": exemplar_count,
        "forensics_staleness_s": forensics_staleness,
        "georepl_lag_bytes": georepl_lag_bytes,
        "georepl_lag_seconds": georepl_lag_seconds,
        "arena_resident_bytes": arena_resident,
        "arena_read_retries_per_s": arena_retries / dt_s,
        "arena_load_factor": arena_load_factor,
        "arena_publish_seconds": arena_publish_s,
        "arena_batch_rows_per_s": batch_rows / dt_s,
        "arena_cas_success_per_s": cas_success / dt_s,
        "arena_cas_retry_per_s": cas_retry / dt_s,
        "edge_open_connections": edge_conns,
        "edge_coalesce_per_s": edge_coalesce / dt_s,
        "edge_hedges_per_s": edge_hedges / dt_s,
        "edge_hedge_wins_per_s": edge_hedge_wins / dt_s,
        "edge_shed_per_s": edge_shed / dt_s,
        "edge_p99_s": (snapshot_quantile(edge_window, 99)
                       if edge_window else None),
        "push_subs_active": push_subs,
        "push_deltas_per_s": push_deltas / dt_s,
        "push_notifications_per_s": push_notifications / dt_s,
        "push_fanout_ratio": push_fanout,
        "push_resumes_per_s": push_resumes / dt_s,
        "push_ring_evictions_per_s": push_evictions / dt_s,
        "push_p99_s": (snapshot_quantile(push_window, 99)
                       if push_window else None),
        "prof_samples_per_s": prof_samples / dt_s,
        "process_cpu_per_s": process_cpu / dt_s,
        "native_self_cpu_per_s": native_self / dt_s,
        "dt_s": dt_s,
        "requests": requests,
    }


def push_freshness(samples: Sequence[Tuple[float, dict]]) -> dict:
    """Reset-aware update→push freshness over a SERIES of fleet scrapes.

    ``fleet_signals`` differences two endpoint snapshots, which is blind
    to counter resets in between: an elastic generation cutover (or any
    worker restart) replaces the processes whose counters held the
    window's history, so ``after - before`` clamps to zero and the
    latency histogram's delta goes empty — a healthy push plane reads as
    a silent one.  Here each CONSECUTIVE scrape pair contributes its
    increment instead, with the standard reset rule: when a fleet-merged
    total shrinks, the new snapshot's value IS the increment (the
    replacement processes started from zero, so their total is exactly
    what they did since).  While old and new generations are briefly
    co-registered their merged total covers both, so a cutover costs at
    most one scrape interval of re-counted new-generation traffic — an
    acceptable overcount for a freshness gate, never an undercount.

    ``samples`` are ``(unix_ts, fleet_snapshot)`` pairs as collected by
    the rehearsal's sampler (obs/workload.py).  Returns::

        {"deltas": accumulated tpums_push_deltas_total increments,
         "p99_s": update→push p99 over the accumulated window ladder
                  (None when no observation landed),
         "dt_s": wall span of the series}
    """
    def _total(snap, name):
        return sum(c["value"] for c in snap.get("counters", [])
                   if c["name"] == name)

    def _hists(snap):
        return {tuple(sorted(h.get("labels", {}).items())): h
                for h in snap.get("histograms", [])
                if h["name"] == "tpums_push_latency_seconds"}

    deltas = 0.0
    window: Optional[dict] = None
    for (_, before), (_, after) in zip(samples, samples[1:]):
        inc = _total(after, "tpums_push_deltas_total") \
            - _total(before, "tpums_push_deltas_total")
        if inc < 0:  # reset: the survivors' total is the increment
            inc = _total(after, "tpums_push_deltas_total")
        deltas += inc
        prev = _hists(before)
        for key, h in _hists(after).items():
            p = prev.get(key)
            if p is None or h["count"] < p["count"] \
                    or list(p["le"]) != list(h["le"]):
                p = {"counts": [0] * len(h["counts"]), "count": 0,
                     "sum": 0.0}
            dc = h["count"] - p["count"]
            if dc <= 0:
                continue
            dcounts = [a - b for a, b in zip(h["counts"], p["counts"])]
            if any(d < 0 for d in dcounts):  # partial reset mid-merge
                dcounts, dc = list(h["counts"]), h["count"]
            dsum = max(h["sum"] - p["sum"], 0.0)
            if window is None:
                window = {"name": "push_window", "le": list(h["le"]),
                          "counts": dcounts, "count": dc, "sum": dsum}
            elif window["le"] == list(h["le"]):
                window["counts"] = [a + b for a, b in
                                    zip(window["counts"], dcounts)]
                window["count"] += dc
                window["sum"] += dsum
    return {
        "deltas": deltas,
        "p99_s": (snapshot_quantile(window, 99) if window else None),
        "dt_s": (samples[-1][0] - samples[0][0]) if len(samples) > 1
                else 0.0,
    }


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    prom = "--prom" in argv
    raw = "--raw" in argv
    result = scrape_fleet()
    if prom:
        sys.stdout.write(render_prometheus(result["fleet"]))
    elif raw:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(json.dumps({
            "scraped": result["scraped"],
            "unreachable": result["unreachable"],
            "per_shard": result["per_shard"],
            "fleet": result["fleet"],
        }, indent=2, default=str))
    return 0 if result["scraped"] or not result["unreachable"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
