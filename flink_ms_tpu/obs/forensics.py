"""Tail-latency forensics: span spills -> causal trees -> critical-path
self-times -> automated slow-vs-fast diffing.

The tracing layer (``obs.tracing``) leaves span records behind — client
RPCs, server replies (both planes), microbatch queue-wait/device stages,
fan-out legs, update-plane apply/publish/visible — each carrying
``tid``/``sid``/``psid``/``t0``/``dur_s``.  This module turns those flat
JSONL spills into answers to "why is p99 40x p50?":

- ``collect`` gathers spill files fleet-wide (paths or globs, rotated
  siblings included) plus optionally the in-process ring.
- ``assemble`` groups events per trace id and links spans into trees via
  ``psid`` (spans whose parent never landed become roots — spills are
  best-effort, trees must tolerate missing interior nodes).
- ``critical_path`` attributes each trace's wall time to stages by SELF
  time: a span's duration minus its children's (clipped at zero), so a
  server span that spent 9 of its 10ms inside a device-dispatch child
  charges 1ms to itself and 9ms to the child.
- ``diff_slow_fast`` splits traces into the slow tail (>= ``slow_q``
  quantile of total duration) and the median band, averages per-stage
  self-time in each, and ranks stages by the delta — "stage X contributes
  N µs to the tail" as data, not speculation.
- ``incident_context`` packages exemplar tids + their critical paths for
  the watch plane to attach to a firing latency alert.

CLI::

    python -m flink_ms_tpu.obs.forensics '/tmp/spill.jsonl*' --top 5
    python -m flink_ms_tpu.obs.forensics spill.jsonl --json

Stage naming: ``kind`` alone for client/update spans, ``kind:VERB`` for
server replies (so a slow TOPKV is distinguishable from a slow GET).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import sys
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import metrics as _metrics
from . import tracing as _tracing


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

def expand_paths(paths: Sequence[str]) -> List[str]:
    """Expand globs and add rotated siblings (``path.1``..) of literal
    paths, de-duplicated in first-seen order."""
    out: List[str] = []
    seen = set()
    for p in paths:
        hits = sorted(_glob.glob(p)) if any(ch in p for ch in "*?[") \
            else [p]
        for h in hits:
            for cand in [h] + sorted(_glob.glob(h + ".[0-9]*")):
                if cand not in seen:
                    seen.add(cand)
                    out.append(cand)
    return out


def collect(paths: Sequence[str],
            include_ring: bool = False) -> List[dict]:
    """Load every event from the given spill files/globs (malformed lines
    skipped, missing files tolerated), time-ordered.  Publishes
    ``tpums_forensics_last_collect_ts`` so ``fleet_signals`` can report
    forensics staleness."""
    events: List[dict] = []
    for path in expand_paths(paths):
        try:
            events.extend(_tracing.load_events(path))
        except OSError:
            continue
    if include_ring:
        events.extend(_tracing.recent_events())
    events.sort(key=lambda e: e.get("ts", 0.0))
    reg = _metrics.get_registry()
    reg.gauge("tpums_forensics_last_collect_ts").set(time.time())
    reg.gauge("tpums_forensics_events").set(len(events))
    return events


# ---------------------------------------------------------------------------
# tree assembly
# ---------------------------------------------------------------------------

def stage_name(ev: dict) -> str:
    kind = str(ev.get("kind", "?"))
    verb = ev.get("verb")
    return f"{kind}:{verb}" if verb else kind


def _span_bounds(ev: dict) -> Tuple[float, float]:
    """(t0, t_end) for a span event; t0 falls back to ts - dur for spills
    that predate the t0 field."""
    dur = float(ev.get("dur_s") or 0.0)
    t0 = ev.get("t0")
    if t0 is None:
        t0 = float(ev.get("ts", 0.0)) - dur
    return float(t0), float(t0) + dur


class TraceTree:
    """One trace's spans linked parent->child.  ``spans`` maps sid ->
    event; ``children`` maps sid -> [sid]; ``roots`` are spans whose
    parent is absent (missing interior spans promote their subtrees to
    roots rather than dropping them)."""

    __slots__ = ("tid", "spans", "children", "roots", "annotations")

    def __init__(self, tid: str):
        self.tid = tid
        self.spans: Dict[str, dict] = {}
        self.children: Dict[str, List[str]] = {}
        self.roots: List[str] = []
        self.annotations: List[dict] = []  # point events (no sid/dur)

    def total_s(self) -> float:
        """Wall extent of the trace: last span end minus first span start
        (NOT the sum of durations — concurrent fan-out legs overlap)."""
        if not self.spans:
            return 0.0
        starts, ends = zip(*(_span_bounds(e) for e in self.spans.values()))
        return max(0.0, max(ends) - min(starts))

    def self_times(self) -> Dict[str, float]:
        """stage -> summed SELF seconds across this trace's spans."""
        out: Dict[str, float] = {}
        for sid, ev in self.spans.items():
            dur = float(ev.get("dur_s") or 0.0)
            child_dur = sum(
                float(self.spans[c].get("dur_s") or 0.0)
                for c in self.children.get(sid, ()))
            self_s = max(0.0, dur - child_dur)
            stage = stage_name(ev)
            out[stage] = out.get(stage, 0.0) + self_s
        return out

    def render(self, indent: str = "  ") -> str:
        """Human tree: one line per span, children indented under
        parents, ordered by start time."""
        lines: List[str] = [f"trace {self.tid}  "
                            f"({self.total_s() * 1e3:.3f} ms, "
                            f"{len(self.spans)} spans)"]

        def walk(sid: str, depth: int) -> None:
            ev = self.spans[sid]
            dur = float(ev.get("dur_s") or 0.0)
            extra = ""
            if ev.get("queue_wait_s") is not None:
                extra += f" queue={float(ev['queue_wait_s']) * 1e6:.0f}us"
            if ev.get("plane"):
                extra += f" [{ev['plane']}]"
            lines.append(f"{indent * (depth + 1)}{stage_name(ev)}  "
                         f"{dur * 1e6:.0f}us{extra}")
            for c in sorted(self.children.get(sid, ()),
                            key=lambda s: _span_bounds(self.spans[s])[0]):
                walk(c, depth + 1)

        for r in sorted(self.roots,
                        key=lambda s: _span_bounds(self.spans[s])[0]):
            walk(r, 0)
        return "\n".join(lines)


def assemble(events: Iterable[dict]) -> Dict[str, TraceTree]:
    """Group events by tid and link spans into trees.  An event is a span
    iff it carries ``sid``; duplicate sids keep the longer duration (a
    retried spill write, not two spans)."""
    trees: Dict[str, TraceTree] = {}
    for ev in events:
        tid = ev.get("tid")
        if not tid:
            continue
        tree = trees.get(tid)
        if tree is None:
            tree = trees[tid] = TraceTree(tid)
        sid = ev.get("sid")
        if not sid:
            if ev.get("dur_s") is None:
                tree.annotations.append(ev)
            continue
        old = tree.spans.get(sid)
        if old is None or float(ev.get("dur_s") or 0.0) > float(
                old.get("dur_s") or 0.0):
            tree.spans[sid] = ev
    for tree in trees.values():
        for sid, ev in tree.spans.items():
            psid = ev.get("psid")
            if psid and psid in tree.spans and psid != sid:
                tree.children.setdefault(psid, []).append(sid)
            else:
                tree.roots.append(sid)
    return trees


# ---------------------------------------------------------------------------
# critical path + slow/fast diff
# ---------------------------------------------------------------------------

def critical_path(tree: TraceTree, top: int = 0) -> List[dict]:
    """Ranked stage self-times for ONE trace: where its wall time went."""
    total = tree.total_s()
    rows = [{"stage": st, "self_s": round(s, 9),
             "share": round(s / total, 4) if total > 0 else 0.0}
            for st, s in tree.self_times().items()]
    rows.sort(key=lambda r: -r["self_s"])
    return rows[:top] if top else rows


def _quantile(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def diff_slow_fast(trees: Dict[str, TraceTree],
                   slow_q: float = 0.9,
                   fast_band: Tuple[float, float] = (0.25, 0.75)
                   ) -> dict:
    """Split traces into the slow tail (total duration >= the ``slow_q``
    quantile) and the median band (``fast_band`` quantiles), average each
    stage's self-time within each set, and rank stages by the delta.

    Returns::

        {"n_traces", "slow_n", "fast_n", "slow_mean_s", "fast_mean_s",
         "quantiles": {"p50", "p90", "p99"},
         "stages": [{"stage", "slow_self_s", "fast_self_s", "delta_s",
                     "delta_share"}, ...],   # delta-ranked, worst first
         "slow_tids": [tid, ...]}            # slowest first
    """
    totals = sorted(((t.total_s(), tid) for tid, t in trees.items()),
                    key=lambda p: p[0])
    vals = [v for v, _ in totals]
    out = {"n_traces": len(totals), "slow_n": 0, "fast_n": 0,
           "slow_mean_s": 0.0, "fast_mean_s": 0.0,
           "quantiles": {"p50": round(_quantile(vals, 0.5), 9),
                         "p90": round(_quantile(vals, 0.9), 9),
                         "p99": round(_quantile(vals, 0.99), 9)},
           "stages": [], "slow_tids": []}
    if len(totals) < 4:  # not enough traces to split meaningfully
        return out
    slow_cut = _quantile(vals, slow_q)
    lo_cut = _quantile(vals, fast_band[0])
    hi_cut = _quantile(vals, fast_band[1])
    slow = [tid for v, tid in totals if v >= slow_cut]
    fast = [tid for v, tid in totals if lo_cut <= v <= hi_cut
            and v < slow_cut]
    if not slow or not fast:
        return out

    def mean_stages(tids: List[str]) -> Tuple[Dict[str, float], float]:
        acc: Dict[str, float] = {}
        tot = 0.0
        for tid in tids:
            tot += trees[tid].total_s()
            for st, s in trees[tid].self_times().items():
                acc[st] = acc.get(st, 0.0) + s
        n = float(len(tids))
        return {st: s / n for st, s in acc.items()}, tot / n

    slow_means, slow_total = mean_stages(slow)
    fast_means, fast_total = mean_stages(fast)
    gap = max(slow_total - fast_total, 1e-12)
    stages = []
    for st in set(slow_means) | set(fast_means):
        d = slow_means.get(st, 0.0) - fast_means.get(st, 0.0)
        stages.append({"stage": st,
                       "slow_self_s": round(slow_means.get(st, 0.0), 9),
                       "fast_self_s": round(fast_means.get(st, 0.0), 9),
                       "delta_s": round(d, 9),
                       "delta_share": round(d / gap, 4)})
    stages.sort(key=lambda r: -r["delta_s"])
    slow_set = set(slow)
    out.update({
        "slow_n": len(slow), "fast_n": len(fast),
        "slow_mean_s": round(slow_total, 9),
        "fast_mean_s": round(fast_total, 9),
        "stages": stages,
        "slow_tids": [tid for _, tid in reversed(totals)
                      if tid in slow_set],
    })
    return out


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def report(paths: Sequence[str],
           slow_q: float = 0.9,
           include_ring: bool = False,
           top: int = 10) -> dict:
    """Collect -> assemble -> diff, as one JSON-ready dict."""
    events = collect(paths, include_ring=include_ring)
    trees = assemble(events)
    diff = diff_slow_fast(trees, slow_q=slow_q)
    slowest = []
    for tid in diff["slow_tids"][:3]:
        slowest.append({"tid": tid,
                        "total_s": round(trees[tid].total_s(), 9),
                        "critical_path": critical_path(trees[tid],
                                                       top=5)})
    return {"events": len(events), "traces": len(trees),
            "slow_q": slow_q, "diff": {**diff,
                                       "stages": diff["stages"][:top]},
            "slowest": slowest}


def render_human(rep: dict) -> str:
    """The report as a terminal summary — ranked "stage X contributes
    N µs to the tail" lines plus the slowest trace's critical path."""
    d = rep["diff"]
    q = d["quantiles"]
    lines = [
        f"forensics: {rep['traces']} traces / {rep['events']} events  "
        f"p50={q['p50'] * 1e3:.3f}ms p90={q['p90'] * 1e3:.3f}ms "
        f"p99={q['p99'] * 1e3:.3f}ms",
    ]
    if not d["stages"]:
        lines.append("  (not enough traces for a slow-vs-fast split)")
        return "\n".join(lines)
    lines.append(
        f"slow tail (n={d['slow_n']}, mean "
        f"{d['slow_mean_s'] * 1e3:.3f}ms) vs median band "
        f"(n={d['fast_n']}, mean {d['fast_mean_s'] * 1e3:.3f}ms):")
    for i, st in enumerate(d["stages"], 1):
        if st["delta_s"] <= 0:
            break
        lines.append(
            f"  #{i} {st['stage']}: +{st['delta_s'] * 1e6:.0f}us "
            f"({st['delta_share'] * 100:.0f}% of the gap; "
            f"slow {st['slow_self_s'] * 1e6:.0f}us vs "
            f"fast {st['fast_self_s'] * 1e6:.0f}us)")
    for s in rep.get("slowest", [])[:1]:
        lines.append(f"slowest trace {s['tid']} "
                     f"({s['total_s'] * 1e3:.3f}ms):")
        for row in s["critical_path"]:
            lines.append(f"    {row['stage']}: "
                         f"{row['self_s'] * 1e6:.0f}us "
                         f"({row['share'] * 100:.0f}%)")
    return "\n".join(lines)


def incident_context(exemplar_tids: Sequence[str],
                     trees: Optional[Dict[str, TraceTree]] = None,
                     paths: Optional[Sequence[str]] = None,
                     max_tids: int = 4) -> dict:
    """Forensics payload for a firing latency alert: the exemplar tids the
    histogram retained plus each one's critical path (when its spans are
    collectable).  ``trees`` wins over ``paths``; with neither, falls back
    to the in-process ring."""
    if trees is None:
        events = collect(paths or [], include_ring=True)
        trees = assemble(events)
    tids = [t for t in dict.fromkeys(exemplar_tids) if t][:max_tids]
    paths_out = []
    for tid in tids:
        tree = trees.get(tid)
        if tree is not None and tree.spans:
            paths_out.append({"tid": tid,
                              "total_s": round(tree.total_s(), 9),
                              "critical_path": critical_path(tree, top=4)})
    return {"exemplar_tids": tids, "critical_path": paths_out}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flink_ms_tpu.obs.forensics",
        description="Assemble span spills into trees and diff the slow "
                    "tail against the median band.")
    ap.add_argument("paths", nargs="+",
                    help="span spill files or globs (rotated .N siblings "
                         "are picked up automatically)")
    ap.add_argument("--slow-quantile", type=float, default=0.9,
                    help="tail cut for the slow set (default 0.9)")
    ap.add_argument("--top", type=int, default=10,
                    help="stages to keep in the ranked diff")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--tree", metavar="TID",
                    help="render one trace's span tree and exit")
    args = ap.parse_args(argv)
    if args.tree:
        trees = assemble(collect(args.paths))
        tree = trees.get(args.tree)
        if tree is None:
            print(f"no spans for tid {args.tree}", file=sys.stderr)
            return 1
        print(tree.render())
        return 0
    rep = report(args.paths, slow_q=args.slow_quantile, top=args.top)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print(render_human(rep))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
