"""Synthetic ALS model generator — counterpart of ``ALSModelGenerator``
(``model-generator/src/main/scala/de/tub/it4bi/ALSModelGenerator.scala``).

"Only for testing the latency and throughput. Not for quality."
(ALSModelGenerator.scala:12).  Row format and id conventions match the
reference: ids 1..numUsers typed U then 1..numItems typed I, factor entries
drawn from the same heavy-tailed ratio distribution
``nextDouble()/nextDouble() * latentFactors`` (ALSModelGenerator.scala:28-32).

Generation runs as a jitted JAX program in batches (device RNG), so the
10M-user scale envelope in BASELINE.md is device-bound, not Python-bound.
``--parallelism p`` (default 2, reference parity) writes a directory of p
part files named "1".."p" exactly like Flink's parallel ``writeAsText``.
"""

from __future__ import annotations

import os
import sys
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..core import formats as F
from ..core.params import Params

_BATCH = 1 << 16


def _random_factor_batch(key, n_rows: int, latent: int) -> np.ndarray:
    a, b = jax.random.split(key)
    num = jax.random.uniform(a, (n_rows, latent), dtype=jnp.float32)
    den = jax.random.uniform(b, (n_rows, latent), dtype=jnp.float32)
    # same shape as the reference's nextDouble()/nextDouble() * latentFactors:
    # ratio of uniforms, scaled (heavy-tailed; occasionally huge)
    return np.asarray(num / jnp.maximum(den, 1e-12) * latent, dtype=np.float64)


def generate_rows(
    n: int, category: str, latent: int, seed: int = 0
) -> Iterator[str]:
    """Rows ``id,U|I,f1;...`` for ids 1..n (reference ids are 1-based —
    ALSModelGenerator.scala:47-53)."""
    from ..parallel.mesh import honor_platform_env

    honor_platform_env()  # an explicit JAX_PLATFORMS pin (cpu fallback,
    # accelerator tunnel down) must reach the device RNG here too
    key = jax.random.PRNGKey(seed)
    done = 0
    while done < n:
        m = min(_BATCH, n - done)
        key, sub = jax.random.split(key)
        block = _random_factor_batch(sub, m, latent)
        for j in range(m):
            yield F.format_als_row(done + j + 1, category, block[j])
        done += m


def _write_parallel(path: str, rows: Iterator[str], parallelism: int) -> None:
    if parallelism <= 1:
        F.write_lines(path, rows)
        return
    os.makedirs(path, exist_ok=True)
    files = [open(os.path.join(path, str(i + 1)), "w") for i in range(parallelism)]
    try:
        for n, row in enumerate(rows):
            f = files[n % parallelism]
            f.write(row)
            f.write("\n")
    finally:
        for f in files:
            f.close()


def run(params: Params) -> None:
    num_users = int(params.get_required("numUsers"))
    num_items = int(params.get_required("numItems"))
    latent = int(params.get_required("latentFactors"))
    p = params.get_int("parallelism", 2)
    seed = params.get_int("seed", 0)

    def all_rows():
        yield from generate_rows(num_users, F.USER, latent, seed)
        yield from generate_rows(num_items, F.ITEM, latent, seed + 1)

    if params.has("output"):
        _write_parallel(params.get_required("output"), all_rows(), p)
    else:
        print("Printing results to stdout. Use --output to specify output location")
        for row in all_rows():
            print(row)


def main(argv=None) -> None:
    run(Params.from_args(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    main()
