"""Synthetic SVM model generator — counterpart of ``SVMModelGenerator``
(``model-generator/src/main/scala/de/tub/it4bi/SVMModelGenerator.scala``).

Emits range-partitioned rows ``bucket,idx:w;...`` for buckets
0..numFeatures/range inclusive, each bucket covering keys
``bucket*range .. bucket*range + range-1`` (0-based, reference parity —
SVMModelGenerator.scala:27-40; note this differs from SVMImpl's 1-based
trained-model indices, a reference quirk preserved as-is).  ~50% of weights
are exactly 0 (``nextBoolean`` gate :32-35), the rest uniform in (-10, 10)
(stand-in for the reference's dyadic-bisection sampler :45-52 — both are
symmetric about 0 and bounded; the generator is documented "Not for
quality" :12).
"""

from __future__ import annotations

import os
import sys
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..core import formats as F
from ..core.params import Params


def generate_bucket_rows(num_features: int, range_: int, seed: int = 0) -> Iterator[str]:
    from ..parallel.mesh import honor_platform_env

    honor_platform_env()  # explicit JAX_PLATFORMS pin must reach the RNG
    n_buckets = num_features // range_ + 1
    key = jax.random.PRNGKey(seed)
    for bucket in range(n_buckets):
        key, kz, kw = jax.random.split(key, 3)
        zero = np.asarray(jax.random.bernoulli(kz, 0.5, (range_,)))
        w = np.asarray(
            jax.random.uniform(kw, (range_,), minval=-10.0, maxval=10.0)
        )
        start = bucket * range_
        parts = []
        for j in range(range_):
            v = 0 if bool(zero[j]) else float(w[j])
            parts.append(f"{start + j}:{_fmt(v)}")
        yield f"{bucket}," + ";".join(parts)


def _fmt(v) -> str:
    # reference prints Scala Int 0 for zeroed weights ("i:0"), doubles otherwise
    return "0" if v == 0 else repr(float(v))


def run(params: Params) -> None:
    num_features = int(params.get_required("numFeatures"))
    range_ = int(params.get_required("range"))
    p = params.get_int("parallelism", 2)
    seed = params.get_int("seed", 0)

    rows = generate_bucket_rows(num_features, range_, seed)
    if params.has("output"):
        from .als_model_generator import _write_parallel

        _write_parallel(params.get_required("output"), rows, p)
    else:
        print("Printing results to stdout. Use --output to specify output location")
        for row in rows:
            print(row)


def main(argv=None) -> None:
    run(Params.from_args(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    main()
